"""Tests for activations, initializers, losses, updaters, flat-param utils."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.activations import ACTIVATIONS, get_activation
from deeplearning4j_tpu.nn.initializers import INITIALIZERS, get_initializer
from deeplearning4j_tpu.nn.losses import LOSSES, get_loss
from deeplearning4j_tpu.nn.updaters import (
    Adam, Nesterovs, Sgd, StepSchedule, build_optimizer, get_updater,
)
from deeplearning4j_tpu.util.params import (
    flat_to_params, num_params, params_to_flat,
)


class TestActivations:
    def test_known_values(self):
        x = jnp.array([-2.0, -0.5, 0.0, 0.5, 2.0])
        np.testing.assert_allclose(get_activation("relu")(x),
                                   [0, 0, 0, 0.5, 2.0])
        np.testing.assert_allclose(get_activation("identity")(x), x)
        np.testing.assert_allclose(get_activation("hardtanh")(x),
                                   [-1, -0.5, 0, 0.5, 1.0])
        np.testing.assert_allclose(get_activation("cube")(x),
                                   [-8, -0.125, 0, 0.125, 8.0], rtol=1e-6)

    def test_softmax_normalizes(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 10))
        y = get_activation("softmax")(x)
        np.testing.assert_allclose(jnp.sum(y, axis=-1), jnp.ones(4), rtol=1e-5)

    @pytest.mark.parametrize("name", sorted(ACTIVATIONS))
    def test_all_finite_and_differentiable(self, name):
        x = jnp.linspace(-3, 3, 32).reshape(4, 8)
        fn = get_activation(name)
        y = fn(x)
        assert y.shape == x.shape
        assert jnp.all(jnp.isfinite(y))
        g = jax.grad(lambda a: jnp.sum(fn(a)))(x)
        assert jnp.all(jnp.isfinite(g))


class TestInitializers:
    @pytest.mark.parametrize("name", [n for n in sorted(INITIALIZERS)
                                      if n != "identity"])
    def test_shapes_and_scale(self, name):
        key = jax.random.PRNGKey(42)
        w = get_initializer(name)(key, (64, 32), 64, 32)
        assert w.shape == (64, 32)
        assert jnp.all(jnp.isfinite(w))

    def test_xavier_variance(self):
        key = jax.random.PRNGKey(1)
        w = get_initializer("xavier")(key, (500, 500), 500, 500)
        expected_std = np.sqrt(2.0 / 1000)
        assert abs(float(jnp.std(w)) - expected_std) < 0.1 * expected_std

    def test_identity(self):
        w = get_initializer("identity")(jax.random.PRNGKey(0), (5, 5), 5, 5)
        np.testing.assert_allclose(w, jnp.eye(5))


class TestLosses:
    def test_mse_known(self):
        labels = jnp.array([[1.0, 2.0]])
        preout = jnp.array([[1.5, 2.5]])
        assert abs(float(get_loss("mse")(labels, preout)) - 0.25) < 1e-6

    def test_mcxent_matches_manual(self):
        labels = jnp.array([[0.0, 1.0, 0.0]])
        logits = jnp.array([[0.1, 2.0, -1.0]])
        expected = -jax.nn.log_softmax(logits)[0, 1]
        got = get_loss("mcxent")(labels, logits, "softmax")
        np.testing.assert_allclose(got, expected, rtol=1e-6)

    def test_sparse_equals_dense_mcxent(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (8, 5))
        idx = jnp.array([0, 1, 2, 3, 4, 0, 1, 2])
        dense = jax.nn.one_hot(idx, 5)
        a = get_loss("mcxent")(dense, logits, "softmax")
        b = get_loss("sparse_mcxent")(idx, logits, "softmax")
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_xent_stable_at_extremes(self):
        labels = jnp.array([[1.0], [0.0]])
        z = jnp.array([[100.0], [-100.0]])
        v = get_loss("xent")(labels, z, "sigmoid")
        assert jnp.isfinite(v) and float(v) < 1e-4

    def test_mask_zeroes_contribution(self):
        labels = jnp.ones((2, 3, 4))
        preout = jnp.zeros((2, 3, 4))
        mask = jnp.array([[1.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
        full = get_loss("mse")(labels, preout, "identity")
        masked = get_loss("mse")(labels, preout, "identity", mask=mask)
        np.testing.assert_allclose(masked, full, rtol=1e-6)  # same per-step err

    @pytest.mark.parametrize("name", sorted(LOSSES))
    def test_all_losses_differentiable(self, name):
        key = jax.random.PRNGKey(3)
        if name == "sparse_mcxent":
            labels = jnp.array([0, 1, 2, 3])
        elif name in ("hinge", "squared_hinge"):
            labels = jnp.sign(jax.random.normal(key, (4, 4)))
        else:
            labels = jax.nn.softmax(jax.random.normal(key, (4, 4)))
        preout = jax.random.normal(jax.random.PRNGKey(4), (4, 4))
        fn = get_loss(name)
        g = jax.grad(lambda z: fn(labels, z))(preout)
        assert jnp.all(jnp.isfinite(g))


class TestUpdaters:
    def test_resolve(self):
        assert isinstance(get_updater("adam"), Adam)
        assert isinstance(get_updater(("sgd", 0.5)), Sgd)
        assert get_updater(("sgd", 0.5)).learning_rate == 0.5

    def test_sgd_step(self):
        tx = build_optimizer(Sgd(learning_rate=0.1))
        params = {"w": jnp.ones(3)}
        st = tx.init(params)
        grads = {"w": jnp.ones(3)}
        updates, _ = tx.update(grads, st, params)
        np.testing.assert_allclose(updates["w"], -0.1 * jnp.ones(3), rtol=1e-6)

    def test_schedule(self):
        s = StepSchedule(initial=1.0, decay_rate=0.5, step=10).to_optax()
        assert s(0) == 1.0
        assert s(10) == 0.5
        assert s(25) == 0.25

    def test_nesterov_converges_quadratic(self):
        tx = build_optimizer(Nesterovs(learning_rate=0.05, momentum=0.9))
        params = {"w": jnp.array([5.0])}
        st = tx.init(params)
        for _ in range(100):
            g = {"w": 2 * params["w"]}
            up, st = tx.update(g, st, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, up)
        assert abs(float(params["w"][0])) < 1e-2


class TestFlatParams:
    def test_roundtrip(self):
        params = {"0": {"W": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)},
                  "1": {"W": jnp.full((3, 2), 2.0)},
                  "10": {"b": jnp.zeros(2)}}
        flat = params_to_flat(params)
        assert flat.shape == (num_params(params),)
        back = flat_to_params(flat, params)
        for k in params:
            for p in params[k]:
                np.testing.assert_allclose(back[k][p], params[k][p])

    def test_canonical_order_numeric(self):
        params = {"2": {"a": jnp.array([2.0])}, "10": {"a": jnp.array([10.0])},
                  "1": {"a": jnp.array([1.0])}}
        flat = params_to_flat(params)
        np.testing.assert_allclose(flat, [1.0, 2.0, 10.0])


class TestHostCast:
    """_as_jnp host-side 16-bit cast: halves H2D bytes for bf16 compute
    and must be bit-identical to the transfer-then-device-cast path."""

    @staticmethod
    def _spy_transfer_dtype(monkeypatch):
        """Record the dtype of whatever _as_jnp hands to jnp.asarray —
        the observable that distinguishes host-cast from device-cast."""
        import deeplearning4j_tpu.nn.multilayer as ml
        seen = {}
        real = ml.jnp.asarray

        def spy(a, *args, **kwargs):
            seen["dtype"] = getattr(a, "dtype", None)
            return real(a, *args, **kwargs)

        monkeypatch.setattr(ml.jnp, "asarray", spy)
        return seen

    def test_bf16_host_cast_bitwise_matches_device_cast(self, monkeypatch):
        from deeplearning4j_tpu.nn.multilayer import _as_jnp
        monkeypatch.setenv("DL4J_TPU_HOST_CAST", "1")
        seen = self._spy_transfer_dtype(monkeypatch)
        rs = np.random.RandomState(0)
        a = (rs.randn(64, 17) * 100).astype(np.float32)
        host = _as_jnp(a, jnp.dtype(jnp.bfloat16))
        assert seen["dtype"] == jnp.bfloat16      # cast BEFORE transfer
        dev = jnp.asarray(a).astype(jnp.bfloat16)
        assert host.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(host).view(np.uint16),
            np.asarray(dev).view(np.uint16))

    def test_kill_switch_and_non_16bit_paths(self, monkeypatch):
        from deeplearning4j_tpu.nn.multilayer import _as_jnp
        monkeypatch.setenv("DL4J_TPU_HOST_CAST", "1")
        seen = self._spy_transfer_dtype(monkeypatch)
        a = np.ones((3, 3), np.float32)
        # f32 compute: no host cast, dtype preserved
        out = _as_jnp(a, jnp.dtype(jnp.float32))
        assert out.dtype == jnp.float32
        assert seen["dtype"] == np.float32
        # masks (dtype=None): untouched
        assert _as_jnp(a).dtype == jnp.float32
        # f64 sources must NOT host-cast (double-rounding via f32 differs)
        _as_jnp(np.ones((2, 2), np.float64), jnp.dtype(jnp.bfloat16))
        assert seen["dtype"] == np.float64
        monkeypatch.setenv("DL4J_TPU_HOST_CAST", "0")
        out = _as_jnp(a, jnp.dtype(jnp.bfloat16))
        assert seen["dtype"] == np.float32        # transferred as f32...
        assert out.dtype == jnp.bfloat16          # ...cast on device
