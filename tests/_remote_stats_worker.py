"""Worker process for the remote stats router test: posts stats records to
a driver-side UIServer over HTTP (no jax import — pure stdlib client)."""
import sys
import time

sys.path.insert(0, sys.argv[2])

from deeplearning4j_tpu.ui.storage import (          # noqa: E402
    RemoteUIStatsStorageRouter, StatsRecord,
)


def main():
    url = sys.argv[1]
    router = RemoteUIStatsStorageRouter(url)
    sid = "remote-sess-1"
    router.put_static_info(StatsRecord(
        session_id=sid, type_id="StatsListener", worker_id="worker-7",
        timestamp=time.time(), data={"model": "mlp", "n_params": 42}))
    for i in range(5):
        router.put_update(StatsRecord(
            session_id=sid, type_id="StatsListener", worker_id="worker-7",
            timestamp=time.time() + i, data={"score": 1.0 / (i + 1),
                                             "iteration": i}))
    ok = router.flush(timeout=20)
    router.close()
    print("FLUSHED" if ok else "FLUSH-TIMEOUT")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
