"""Tiered KV fabric tests (serving/kvfabric.py + the spill/transfer
surgery in kvcache.py, decode.py, server.py).

The load-bearing ones are the greedy-parity trio (local prefill, spill
promote-on-hit, and remote export->import must produce IDENTICAL
tokens) and test_eviction_demotes_before_unindexing — the ordering
contract that makes the host tier a cache and never a data-loss window.
"""
import hashlib
import json

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.serving import kvfabric
from deeplearning4j_tpu.serving.decode import (
    DecodeConfig, ServedLM, ServerDrainingError,
)
from deeplearning4j_tpu.serving.kvcache import KVCacheState
from deeplearning4j_tpu.serving.kvfabric import (
    DIGEST_SEED, FrameError, HostPageStore, chain_digests, check_frame,
    frame_capacity, leading_digest, pack_page, pack_transfer, unpack_page,
    unpack_transfer,
)
from deeplearning4j_tpu.serving.registry import load_servable

ZOO_SRC = ("zoo:TransformerLM?vocab_size=48&n_layers=1&n_embd=32"
           "&n_heads=4&seq_length=32")


def _counter(name, **labels):
    return monitor.counter(name, "x",
                           labels=tuple(labels)).value(**labels)


# =========================================================== digests
def test_chain_digests_identify_prefix_paths():
    keys = [b"aaaa", b"bbbb", b"cccc"]
    digs = chain_digests(keys)
    assert len(digs) == 3 and len(set(digs)) == 3
    # chained: block i's digest commits to every block before it
    assert digs[0] == hashlib.sha256(DIGEST_SEED + b"aaaa").digest()
    assert digs[1] == hashlib.sha256(digs[0] + b"bbbb").digest()
    # the same block under a different predecessor is a DIFFERENT entry
    assert chain_digests([b"xxxx", b"bbbb"])[1] != digs[1]


def test_leading_digest_is_the_block_key_convention():
    t = list(range(10))
    d = leading_digest(t, 4)
    assert d == chain_digests(
        [np.asarray(t[:4], np.int32).tobytes()])[0]
    # prompts shorter than one page own nothing
    assert leading_digest([1, 2, 3], 4) is None
    # the digest covers exactly the first page
    assert leading_digest(t[:4] + [99, 98], 4) == d


# ============================================== per-page frame serde
def _rand(dtype, shape=(1, 4, 2, 3), seed=7):
    rng = np.random.default_rng(seed)
    if np.dtype(dtype) == np.int8:
        return rng.integers(-128, 127, shape, dtype=np.int8)
    return rng.standard_normal(shape, dtype=np.float32).astype(dtype)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_page_frame_roundtrip_bitwise(dtype):
    if dtype == "bfloat16":
        ml_dtypes = pytest.importorskip("ml_dtypes")
        dtype = ml_dtypes.bfloat16
    k, v = _rand(dtype, seed=1), _rand(dtype, seed=2)
    digest = hashlib.sha256(b"page-0").digest()
    frame = pack_page(k, v, digest)
    k2, v2, hdr = unpack_page(frame, expect_digest=digest)
    assert k2.dtype == k.dtype and k2.shape == k.shape
    assert k2.tobytes() == k.tobytes()        # bitwise, not allclose
    assert v2.tobytes() == v.tobytes()
    assert hdr["v"] == kvfabric.VERSION
    # prefix-path mismatch is a hard reject (wrong cache entry)
    with pytest.raises(FrameError):
        unpack_page(frame, expect_digest=hashlib.sha256(b"x").digest())


def test_page_frame_rejects_every_corruption():
    """Fuzz-ish sweep: EVERY single-byte flip and every truncation of a
    frame must raise FrameError — never a crash, never silent garbage."""
    k, v = _rand(np.float32, (1, 2, 2, 2)), _rand(np.float32, (1, 2, 2, 2))
    frame = pack_page(k, v, hashlib.sha256(b"d").digest())
    for i in range(len(frame)):
        bad = bytearray(frame)
        bad[i] ^= 0xFF
        with pytest.raises(FrameError):
            unpack_page(bytes(bad))
        with pytest.raises(FrameError):
            check_frame(bytes(bad))
    for n in range(len(frame)):
        with pytest.raises(FrameError):
            unpack_page(frame[:n])
    # version from the future: clean reject, not a parse attempt
    fut = bytearray(frame)
    fut[4] = 99
    with pytest.raises(FrameError):
        unpack_page(bytes(fut))


def test_transfer_roundtrip_and_wire_rejections():
    ps = 4
    toks = np.arange(8, dtype=np.int32)
    digs = chain_digests([toks[:4].tobytes(), toks[4:].tobytes()])
    frames = [pack_page(_rand(np.float32, seed=i), _rand(np.float32,
                                                         seed=i + 9), d)
              for i, d in enumerate(digs)]
    blob = pack_transfer(toks, frames, ps)
    t2, f2, hdr = unpack_transfer(blob)
    assert t2.tolist() == toks.tolist() and f2 == frames
    assert hdr["page_size"] == ps and hdr["n_frames"] == 2
    # geometry mismatch at pack time is a caller bug, not a FrameError
    with pytest.raises(ValueError):
        pack_transfer(toks[:7], frames, ps)
    # every single-byte flip anywhere in the shipment is caught: the
    # envelope head by its sha, every frame by its own trailer
    for i in range(len(blob)):
        bad = bytearray(blob)
        bad[i] ^= 0xFF
        with pytest.raises(FrameError):
            unpack_transfer(bytes(bad))
    # truncations (sampled: every boundary region matters, steps keep
    # the sweep cheap) — includes mid-frame kill-the-sender cuts
    for n in range(0, len(blob), 7):
        with pytest.raises(FrameError):
            unpack_transfer(blob[:n])


def test_frame_capacity_bounds_real_frames():
    shape = (2, 8, 4, 16)
    cap = frame_capacity(*shape, np.float32)
    k, v = _rand(np.float32, shape), _rand(np.float32, shape)
    frame = pack_page(k, v, hashlib.sha256(b"cap").digest())
    assert len(frame) <= cap


# ======================================================== host store
def test_host_store_lru_eviction_and_demotion_metering():
    clock = [0.0]

    def tick():
        clock[0] += 1.0
        return clock[0]

    st = HostPageStore(2, 64, name="hs-lru", time_fn=tick)
    try:
        ka, kb, kc = (hashlib.sha256(x).digest() for x in
                      (b"a", b"b", b"c"))
        ev0 = _counter("serving_kv_spill_evictions_total", model="hs-lru")
        assert st.put(ka, b"A" * 10) and st.put(kb, b"B" * 20)
        assert len(st) == 2 and st.describe()["bytes_used"] == 30
        # get() is an MRU touch: a now makes b the LRU victim
        assert st.get(ka) == b"A" * 10
        assert st.put(kc, b"C" * 5)
        assert not st.contains(kb) and st.contains(ka)
        assert _counter("serving_kv_spill_evictions_total",
                        model="hs-lru") == ev0 + 1
        assert st.keys() == [kc, ka]          # MRU first
        # oversize frames are metered rejects, never exceptions
        rj0 = _counter("serving_kv_spill_rejects_total", model="hs-lru")
        assert not st.put(kb, b"X" * 65)
        assert _counter("serving_kv_spill_rejects_total",
                        model="hs-lru") == rj0 + 1
        # the fake clock drove deterministic put stamps
        assert st._last_put_at[kc] == 3.0
        st.drop(kc)
        assert not st.contains(kc) and len(st) == 1
    finally:
        st.close()
    assert st.get(ka) is None                 # closed = empty


def test_host_store_rewrite_same_key_reuses_slot():
    st = HostPageStore(1, 32, name="hs-rw")
    try:
        k = hashlib.sha256(b"k").digest()
        assert st.put(k, b"one") and st.put(k, b"two-longer")
        assert st.get(k) == b"two-longer"
        assert st.describe()["bytes_used"] == len(b"two-longer")
        assert len(st) == 1
    finally:
        st.close()


# ==================== eviction order: demote BEFORE unindex (the fix)
class _OrderAssertingStore(HostPageStore):
    """A spill store whose put() asserts the demotion-ordering contract
    at the exact moment it runs: the HBM page being demoted must STILL
    be indexed (in _by_page) and must NOT be on the free list — i.e.
    the host copy becomes durable before the HBM copy is released."""

    def __init__(self, cache, *a, **kw):
        super().__init__(*a, **kw)
        self.cache = cache
        self.order_checks = 0

    def put(self, key, payload):
        c = self.cache
        node = next((n for n in c._by_page.values()
                     if n.digest == key), None)
        assert node is not None, \
            "demotion ran AFTER the page was unindexed"
        assert node.page not in c._free_pages, \
            "demotion ran AFTER the page was freed"
        self.order_checks += 1
        return super().put(key, payload)


def test_eviction_demotes_before_unindexing():
    """Deterministic (fake-clock, fake device) pin on the ordering fix:
    pressure-evicting a retained prefix writes the durable host copy
    FIRST, and only then unindexes + frees the HBM page. A promote-on-
    hit admission then recovers the full prefix from the host tier."""
    clock = [0.0]

    def tick():
        clock[0] += 1.0
        return clock[0]

    c = KVCacheState(slots=2, page_size=4, max_context=16, pool_pages=5,
                     name="evt")                  # 4 usable + dump page
    landed = []
    store = _OrderAssertingStore(
        c, 8, 64, name="evt", time_fn=tick)
    try:
        c.attach_spill(
            store,
            lambda page, digest: b"frame:%d:" % page + digest[:8],
            lambda page, payload, digest: landed.append((page, payload)))
        t = np.arange(8, dtype=np.int32)          # 2 full blocks
        a = c.admit_prompt(t)
        assert a is not None and a.cached_len == 0
        c.register_prefix(a.slot, t)
        c.release(a.slot)
        assert c.retained_pages() == 2
        # pool pressure: 4 pages wanted, 2 free -> evict both retained
        # entries; every put() call re-asserted the ordering contract
        b = c.admit(16)
        assert b is not None
        assert store.order_checks == 2 and len(store) == 2
        assert store._last_put_at                 # fake clock stamped
        c.release(b)
        # promote-on-hit: the same prompt comes back; both blocks land
        # from the host tier (no recompute), ref-pinned then mapped
        pr0 = _counter("serving_kv_spill_promotions_total", model="evt")
        h0 = _counter("serving_kv_spill_hits_total", model="evt")
        a2 = c.admit_prompt(t)
        assert a2 is not None
        # fully-covered prompt: last token recomputes (COW), rest cached
        assert a2.cached_len == 7 and a2.cow_src is not None
        assert len(landed) == 2
        assert _counter("serving_kv_spill_promotions_total",
                        model="evt") == pr0 + 2
        assert _counter("serving_kv_spill_hits_total",
                        model="evt") == h0 + 1
        c.release(a2.slot)
    finally:
        store.close()


def test_promotion_failure_degrades_to_miss():
    """A corrupt host frame (land_fn raises) must degrade to a cache
    miss — dropped from the store, admission still succeeds."""
    c = KVCacheState(slots=2, page_size=4, max_context=16, name="bad")
    store = HostPageStore(4, 64, name="bad")

    def bad_land(page, payload, digest):
        raise FrameError("host frame rotted")

    try:
        c.attach_spill(store, lambda p, d: b"x", bad_land)
        t = np.arange(4, dtype=np.int32)
        a = c.admit_prompt(t)
        c.register_prefix(a.slot, t)
        # place the block's digest in the host tier by hand, then drop
        # the HBM copy so the next admission must promote
        node = next(iter(c._by_page.values()))
        store.put(node.digest, b"frame")
        c.release(a.slot)
        c._drop_subtree_locked(node)              # evict (demote fails
        #                                           too: extract is fake)
        a2 = c.admit_prompt(t)                    # probes, land raises
        assert a2 is not None and a2.cached_len == 0
        assert not store.contains(node.digest)    # corrupt frame dropped
        c.release(a2.slot)
    finally:
        store.close()


# ================================== engine-level: the parity trio
@pytest.fixture(scope="module")
def spill_lm():
    """Spill-enabled LM with a pool small enough that two long prompts
    cannot both stay retained — the second evicts (demotes) the first."""
    lm = ServedLM("spill-lm", load_servable(ZOO_SRC), ZOO_SRC,
                  decode=DecodeConfig(slots=2, page_size=8, pool_pages=8,
                                      spill_pages=8))
    yield lm
    lm.shutdown(drain=False, timeout=5)


@pytest.fixture(scope="module")
def importer_lm():
    """Same weights, separate process-local replica: the decode side of
    a disaggregated transfer."""
    lm = ServedLM("importer-lm", load_servable(ZOO_SRC), ZOO_SRC,
                  decode=DecodeConfig(slots=2, page_size=8,
                                      spill_pages=4))
    yield lm
    lm.shutdown(drain=False, timeout=5)


def _greedy(lm, prompt, n=6):
    req = lm.generate(prompt, max_new_tokens=n, temperature=0.0)
    toks, done = [], None
    while done is None:
        kind, payload = req.events.get(timeout=60)
        if kind == "token":
            toks.append(int(payload))
        elif kind == "error":
            raise payload
        else:
            done = payload
    return toks, done


def test_greedy_parity_local_spill_and_remote(spill_lm, importer_lm):
    """THE fabric acceptance test: one prompt, three KV provenances —
    local prefill, promote-on-hit from the host spill tier, and remote
    pages shipped through export->import — EXACTLY the same greedy
    tokens."""
    prompt = list(range(1, 17))                   # 2 full pages of 8
    other = list(range(30, 46))                   # distinct, same size

    local, d0 = _greedy(spill_lm, prompt)
    assert d0.get("cached_tokens", 0) == 0        # cold: local prefill

    # pressure the pool until the first prompt's retained pages demote
    # to the host tier (pool_pages=8 -> 7 usable; each stream peaks at
    # 3 pages, so the three other-prompt passes evict prompt's pages)
    dem0 = _counter("serving_kv_spill_demotions_total", model="spill-lm")
    for fill in (other, [5, 6] + other[2:], [9, 8] + other[2:]):
        _greedy(spill_lm, fill)
    assert _counter("serving_kv_spill_demotions_total",
                    model="spill-lm") > dem0

    pr0 = _counter("serving_kv_spill_promotions_total", model="spill-lm")
    hot, d1 = _greedy(spill_lm, prompt)
    assert hot == local                           # parity: spill path
    if _counter("serving_kv_spill_promotions_total",
                model="spill-lm") > pr0:
        # promote-on-hit engaged: the prefix came back from host RAM
        assert d1.get("cached_tokens", 0) > 0

    # remote: serialize the pages out of spill-lm, land them in the
    # importer, and decode there — still the same tokens
    blob = spill_lm.export_prefix(prompt)
    assert unpack_transfer(blob)[2]["n_frames"] == 2
    res = importer_lm.import_prefix(blob)
    assert res["adopted"] == 2 and res["tokens"] == 16
    remote, d2 = _greedy(importer_lm, prompt)
    assert remote == local                        # parity: remote path
    assert d2.get("cached_tokens", 0) >= 8        # adopted pages hit
    # idempotent: re-importing the same shipment adopts nothing new
    assert importer_lm.import_prefix(blob)["adopted"] == 0


def test_export_prefix_validates_input(spill_lm):
    with pytest.raises(ValueError):
        spill_lm.export_prefix([1, 2, 3])         # < one full page


def test_import_corrupt_payload_is_a_clean_400_class_error(importer_lm):
    """A corrupt shipment raises FrameError in the CALLER — the
    scheduler thread survives and keeps serving."""
    blob = spill_lm_export = importer_lm.export_prefix(
        list(range(1, 17)))
    for cut in (blob[:25], b"junk" + blob[4:]):
        with pytest.raises(FrameError):
            importer_lm.import_prefix(cut)
    bad = bytearray(spill_lm_export)
    bad[-40] ^= 0xFF                              # inside the last frame
    with pytest.raises(FrameError):
        importer_lm.import_prefix(bytes(bad))
    toks, _ = _greedy(importer_lm, [7, 7, 7])     # still alive
    assert len(toks) == 6


def test_fabric_jobs_propagate_errors_without_killing_scheduler(
        spill_lm):
    class Boom(RuntimeError):
        pass

    def job(engine):
        raise Boom("fabric job failed")

    with pytest.raises(Boom):
        spill_lm.scheduler.run_fabric(job)
    assert spill_lm.scheduler.run_fabric(
        lambda eng: eng.cfg.page_size) == 8        # thread still turning


def test_warm_ledger_covers_fabric_programs(spill_lm, importer_lm):
    """AOT contract holds through spill + transfer traffic: every
    compile (kv_extract/kv_land included) happened inside warmup."""
    def fam_sum(family, model):
        total = 0.0
        for line in monitor.prometheus_text().splitlines():
            if line.startswith(family + "{") \
                    and f'model="{model}"' in line:
                total += float(line.rsplit(" ", 1)[1])
        return total

    for name in ("spill-lm", "importer-lm"):
        compiles = fam_sum("serving_decode_compiles_total", name)
        warmups = fam_sum("serving_decode_warmup_runs_total", name)
        assert compiles and compiles == warmups, \
            f"{name}: {compiles} compiles vs {warmups} warmup runs"
        # the fabric page programs are in the ledger by name
        text = monitor.prometheus_text()
        for prog in ("kv_extract", "kv_land"):
            assert (f'serving_decode_compiles_total{{model="{name}",'
                    f'program="{prog}"}}') in text, (name, prog)


def test_engine_export_transfer_shape(spill_lm):
    """export_prefix produces a version-tagged envelope whose header
    round-trips through JSON (wire-debuggability contract)."""
    blob = spill_lm.export_prefix(list(range(1, 17)))
    tokens, frames, hdr = unpack_transfer(blob)
    assert hdr["v"] == kvfabric.VERSION
    assert json.loads(json.dumps(hdr)) == hdr
    for fr, dig in zip(frames, chain_digests(
            [np.asarray(tokens[:8], np.int32).tobytes(),
             np.asarray(tokens[8:], np.int32).tobytes()])):
        k, v, fh = unpack_page(fr, expect_digest=dig)
        assert k.shape == v.shape and k.shape[1] == 8


# ===================================== router: affinity + disagg unit
def _fake_replicas(n):
    from deeplearning4j_tpu.serving.fleet import Replica
    reps = []
    for i in range(n):
        r = Replica(f"r{i}")
        r.state = "ready"
        r.url = f"http://fake-{i}"
        reps.append(r)
    return reps


def _fake_router(reps, **kw):
    import random

    from deeplearning4j_tpu.serving.router import ResilientRouter
    kw.setdefault("hedge", False)
    kw.setdefault("rng", random.Random(0))
    return ResilientRouter(lambda: [r for r in reps
                                    if r.state == "ready"], **kw)


def test_affinity_pick_owner_fallback_and_none():
    reps = _fake_replicas(3)
    prompt = list(range(8))
    d16 = leading_digest(prompt, 4).hex()[:16]
    reps[1].kv_ownership = {"m": {"block": 4, "digests": [d16]}}
    router = _fake_router(reps)
    # the advertising replica wins (ties break to the owner)
    assert router._affinity_pick("m", prompt, reps) is reps[1]
    # load guard: a strictly-less-loaded rival overrides the owner
    reps[1].inflight_add(3)
    got = router._affinity_pick("m", prompt, reps)
    assert got is not None and got is not reps[1]
    reps[1].inflight_add(-3)
    # nobody advertises this prefix -> no preference (p2c decides)
    assert router._affinity_pick("m", [99] * 8, reps) is None
    # sub-block prompts own nothing
    assert router._affinity_pick("m", [1, 2], reps) is None
    assert _fake_router(reps, affinity=False)._affinity_pick(
        "m", prompt, reps) is None


def test_disagg_prefill_failover_meters_the_dead_peer():
    from deeplearning4j_tpu.serving.router import ReplicaTransportError
    reps = _fake_replicas(3)
    pre, target = reps[0], reps[2]
    calls = []

    def dead_transport(replica, path, body, headers, timeout):
        calls.append((replica.name, path))
        raise ReplicaTransportError(f"{replica.name}: connection refused")

    router = _fake_router(reps, transport=dead_transport)
    f0 = _counter("serving_transfer_failovers_total", model="m")
    assert router._disagg_prefill("m", list(range(8)), [pre],
                                  target) is False
    assert _counter("serving_transfer_failovers_total",
                    model="m") == f0 + 1
    assert calls == [("r0", "/v1/models/m/kv/export")]
    assert pre.inflight() == 0                    # export leg unwound

    def ok_transport(replica, path, body, headers, timeout):
        if path.endswith("/kv/export"):
            return 200, {}, b"BLOB"
        assert body == b"BLOB"
        return 200, {}, b"{}"

    router2 = _fake_router(reps, transport=ok_transport)
    o0 = _counter("serving_transfer_orchestrations_total", model="m")
    assert router2._disagg_prefill("m", list(range(8)), [pre],
                                   target) is True
    assert _counter("serving_transfer_orchestrations_total",
                    model="m") == o0 + 1


def test_run_fabric_rejects_when_draining():
    lm = ServedLM("drain-lm", load_servable(ZOO_SRC), ZOO_SRC,
                  decode=DecodeConfig(slots=2, page_size=8))
    lm.shutdown(drain=False, timeout=5)
    with pytest.raises(ServerDrainingError):
        lm.scheduler.run_fabric(lambda eng: None)
    with pytest.raises(ServerDrainingError):
        lm.export_prefix(list(range(8)))
