"""Interprocedural concurrency analysis + runtime witness + sentinel.

Three layers under test:

1. the static models (analysis/callgraph.py + analysis/concurrency.py):
   call resolution, lock identity, cross-module acquisition-order
   edges, cycle detection, the --lock-graph artifact — including THE
   acceptance pin: the live tree's graph covers the serving fleet's
   lock population (>= 20 locks) with zero cycles;
2. the runtime witness (util/locks.DiagnosedLock): drop-in lock
   behavior, acquisition-order recording, the holder table, and the
   static-vs-runtime cross-check — edges observed while driving the
   real registry/batcher must keep the combined (static ∪ observed)
   graph acyclic, with at least one static edge actually witnessed;
3. the pytest deadlock sentinel (util/sentinel.py): a deliberately
   deadlocked test run dumps BOTH threads' stacks and the lock-holder
   table, then exits 3 instead of hanging mute (slow test: subprocess
   pytest).
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from deeplearning4j_tpu.analysis import core as lint_core
from deeplearning4j_tpu.analysis.callgraph import CallGraph
from deeplearning4j_tpu.analysis.concurrency import (
    ConcurrencyModel, find_cycles, lock_identity,
)
from deeplearning4j_tpu.analysis.rules.lockorder import (
    LockOrderInversionRule,
)
from deeplearning4j_tpu.util import locks as locks_mod
from deeplearning4j_tpu.util.locks import DiagnosedLock

PKG = os.path.join(REPO, "deeplearning4j_tpu")


def _load(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body), encoding="utf-8")
    mod = lint_core.load_module(str(p))
    assert mod is not None
    return mod


# ------------------------------------------------------------- call graph
def test_callgraph_resolves_self_methods_imports_and_nested(tmp_path):
    a = _load(tmp_path, "alpha.py", """\
        def helper():
            pass

        class C:
            def m(self):
                self.n()
                helper()

            def n(self):
                def inner():
                    helper()
                inner()
        """)
    b = _load(tmp_path, "beta.py", """\
        import alpha
        from alpha import helper as h

        def caller():
            alpha.helper()
            h()
        """)
    g = CallGraph([a, b])
    assert g.edges["alpha.C.m"] == {"alpha.C.n", "alpha.helper"}
    # plain-name resolution prefers the nested def chain
    assert "alpha.C.n.inner" in g.edges["alpha.C.n"]
    assert g.edges["alpha.C.n.inner"] == {"alpha.helper"}
    # dotted + aliased from-import both land on the same function
    assert g.edges["beta.caller"] == {"alpha.helper"}


def test_callgraph_reach_chains_depth_limited(tmp_path):
    m = _load(tmp_path, "chainmod.py", """\
        def a():
            b()
        def b():
            c()
        def c():
            d()
        def d():
            pass
        """)
    g = CallGraph([m])
    one = g.reach_chains("chainmod.a", 1)
    assert set(one) == {"chainmod.a", "chainmod.b"}
    three = g.reach_chains("chainmod.a", 3)
    assert three["chainmod.d"] == [
        "chainmod.a", "chainmod.b", "chainmod.c", "chainmod.d"]


def test_lock_identity_scopes(tmp_path):
    mod = _load(tmp_path, "lockid.py", """\
        import threading

        _global_lock = threading.Lock()

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def use(self):
                local_lock = threading.Lock()
                with self._lock:
                    pass
                with local_lock:
                    pass
                with _global_lock:
                    pass
        """)
    model = ConcurrencyModel([mod])
    assert "lockid.C._lock" in model.locks
    assert "lockid._global_lock" in model.locks
    assert "lockid.C.use.<local>local_lock" in model.locks


# ------------------------------------------------------ order graph/cycles
def test_cross_module_lock_cycle_detected(tmp_path):
    m1 = _load(tmp_path, "mod_one.py", """\
        import threading
        import mod_two

        _lock = threading.Lock()

        def take_ours_then_theirs():
            with _lock:
                mod_two.grab()

        def grab():
            with _lock:
                pass
        """)
    m2 = _load(tmp_path, "mod_two.py", """\
        import threading
        import mod_one

        _lock = threading.Lock()

        def take_ours_then_theirs():
            with _lock:
                mod_one.grab()

        def grab():
            with _lock:
                pass
        """)
    model = ConcurrencyModel([m1, m2])
    pairs = {(e.src, e.dst) for e in model.order_edges}
    assert ("mod_one._lock", "mod_two._lock") in pairs
    assert ("mod_two._lock", "mod_one._lock") in pairs
    assert model.cycles() == [["mod_one._lock", "mod_two._lock"]]
    # the rule reports the cycle in BOTH modules, at the call sites
    findings = list(LockOrderInversionRule().check_project(
        lint_core.Project([m1, m2])))
    assert {os.path.basename(f.path) for f in findings} == \
        {"mod_one.py", "mod_two.py"}
    assert all("cycle" in f.message for f in findings)


def test_find_cycles_is_order_insensitive():
    assert find_cycles([("a", "b"), ("b", "c")]) == []
    assert find_cycles([("a", "b"), ("b", "a"), ("x", "y")]) == [
        ["a", "b"]]


# --------------------------------------------- THE live-tree acceptance
def test_live_lock_graph_covers_fleet_and_is_acyclic():
    """Acceptance: the acquisition-order graph over the shipped package
    names >= 20 locks, carries real edges, and has ZERO cycles — the
    fleet has one global lock order."""
    files = lint_core.iter_py_files([PKG])
    mods = [m for m in (lint_core.load_module(f) for f in files) if m]
    model = ConcurrencyModel(mods)
    doc = model.lock_graph_doc()
    assert len(doc["locks"]) >= 20, sorted(doc["locks"])
    assert len(doc["edges"]) >= 5
    assert doc["cycles"] == []
    # the serving stack's adopted DiagnosedLocks appear under their
    # static identities (the runtime witness joins on these names)
    for expected in (
            "deeplearning4j_tpu.serving.registry.ModelRegistry._lock",
            "deeplearning4j_tpu.serving.registry.ServedModel._swap_lock",
            "deeplearning4j_tpu.serving.kvcache.KVCacheState._lock",
            "deeplearning4j_tpu.serving.fleet.ReplicaSupervisor._lock"):
        assert expected in doc["locks"], expected
    # schema: every edge names its evidence
    for e in doc["edges"]:
        assert e["from"] in doc["locks"] or e["to"] in doc["locks"]
        assert ":" in e["site"]


# ------------------------------------------------------------ DiagnosedLock
@pytest.fixture
def recording():
    was = locks_mod.recording_enabled()
    locks_mod.enable_recording(True)
    locks_mod.reset()
    yield
    locks_mod.reset()
    locks_mod.enable_recording(was)


def test_diagnosed_lock_is_a_drop_in_lock(recording):
    lk = DiagnosedLock("t.a")
    assert not lk.locked()
    with lk:
        assert lk.locked()
        assert not lk.acquire(blocking=False)
    assert not lk.locked()
    rl = DiagnosedLock("t.r", reentrant=True)
    with rl:
        with rl:                      # re-entrant: no deadlock
            assert rl.locked()
    assert not rl.locked()


def test_diagnosed_lock_records_edges_and_holders(recording):
    a, b = DiagnosedLock("t.a"), DiagnosedLock("t.b")
    with a:
        table = locks_mod.holder_table()
        assert table["t.a"][0] == threading.current_thread().name
        with b:
            pass
    assert ("t.a", "t.b") in locks_mod.observed_edges()
    assert ("t.b", "t.a") not in locks_mod.observed_edges()
    assert "t.a" not in locks_mod.holder_table()
    # re-entrant self-acquire is not an order edge
    r = DiagnosedLock("t.r", reentrant=True)
    with r:
        with r:
            pass
    assert ("t.r", "t.r") not in locks_mod.observed_edges()
    locks_mod.reset()
    assert locks_mod.observed_edges() == set()


def test_recording_off_is_free_of_bookkeeping():
    locks_mod.enable_recording(False)
    locks_mod.reset()
    a, b = DiagnosedLock("off.a"), DiagnosedLock("off.b")
    with a:
        with b:
            pass
    assert locks_mod.observed_edges() == set()
    assert locks_mod.holder_table() == {}


# ------------------------------------------------- runtime witness check
def test_runtime_witness_agrees_with_static_lock_graph(recording):
    """Drive the REAL serving registry (deploy + swap + predict-warm
    paths) with lock recording on, then cross-check: at least one
    statically-derived edge is witnessed live, and adding every
    observed edge to the static graph introduces NO cycle — runtime
    execution never takes a lock order the static model calls
    inverted."""
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.serving import ModelRegistry

    def net(seed=0):
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init()

    reg = ModelRegistry()
    try:
        reg.deploy("witness", net(0), buckets=(1, 4), max_delay_ms=1.0)
        reg.get("witness").swap(net(1))
    finally:
        reg.shutdown(drain=False)

    observed = locks_mod.observed_edges()
    qualified = {(s, d) for s, d in observed
                 if s.startswith("deeplearning4j_tpu.")
                 and d.startswith("deeplearning4j_tpu.")}
    assert qualified, "no DiagnosedLock edges observed — witness dead"

    files = lint_core.iter_py_files([os.path.join(PKG, "serving")])
    mods = [m for m in (lint_core.load_module(f) for f in files) if m]
    model = ConcurrencyModel(mods)
    static_pairs = {(e.src, e.dst) for e in model.order_edges}
    witnessed_static = static_pairs & qualified
    assert witnessed_static, (
        f"no static edge witnessed live; observed={sorted(qualified)}")
    # the combined graph must stay acyclic: if live execution added the
    # reverse of any static edge, that's a latent AB/BA deadlock the
    # static pass alone could not see
    combined = static_pairs | qualified
    assert find_cycles(combined) == [], (
        f"static ∪ observed has a cycle; observed={sorted(qualified)}")


# ------------------------------------------------------------------- CLI
def _cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         *args],
        capture_output=True, text=True, cwd=REPO, timeout=300)


def test_cli_lock_graph_export(tmp_path):
    out = str(tmp_path / "lockgraph.json")
    r = _cli("--lock-graph", out, os.path.join(PKG, "serving"))
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(open(out).read())
    assert doc["version"] == 1
    assert len(doc["locks"]) >= 10
    assert doc["cycles"] == []
    assert "lock graph" in r.stdout


def test_cli_changed_only_is_clean_or_noop():
    """--changed-only lints exactly the git-diff scope: on a clean tree
    it reports a no-op; on a dirty-but-lint-clean tree it exits 0. (A
    dirty tree with findings fails test_live_tree_is_clean too, so this
    stays green exactly when the gate does.)"""
    r = _cli("--changed-only", "--jobs", "1")
    assert r.returncode == 0, r.stdout + r.stderr
    assert ("nothing to lint" in r.stdout) or ("0 finding" in r.stdout)


def test_cli_jobs_parallel_matches_serial(tmp_path):
    dirty = tmp_path / "d"
    dirty.mkdir()
    (dirty / "one.py").write_text(
        "import os\nv = os.environ.get('DL4J_TPU_X')\n")
    (dirty / "two.py").write_text("x = 1\n")
    for extra in range(6):
        (dirty / f"pad{extra}.py").write_text("y = 2\n")
    serial = _cli("--json", "--jobs", "1", str(dirty))
    parallel = _cli("--json", "--jobs", "2", str(dirty))
    assert serial.returncode == parallel.returncode == 2
    sf = json.loads(serial.stdout)["findings"]
    pf = json.loads(parallel.stdout)["findings"]
    assert sf == pf and len(sf) == 1


# ------------------------------------------------------- deadlock sentinel
DEADLOCK_TEST = """\
import threading
import time

from deeplearning4j_tpu.util.locks import DiagnosedLock

A = DiagnosedLock("sentinel_fixture.A")
B = DiagnosedLock("sentinel_fixture.B")


def test_deliberate_ab_ba_deadlock():
    ready = threading.Barrier(2)

    def one():
        with A:
            ready.wait()
            with B:
                pass

    def two():
        with B:
            ready.wait()
            with A:
                pass

    t1 = threading.Thread(target=one, name="deadlock-one", daemon=True)
    t2 = threading.Thread(target=two, name="deadlock-two", daemon=True)
    t1.start()
    t2.start()
    t1.join()                      # hangs forever: the sentinel fires
"""


@pytest.mark.slow
def test_deadlock_sentinel_dumps_both_stacks_and_holders(tmp_path):
    """The runtime half of the acceptance: a deliberately deadlocked
    test run exits 3 (not a mute hang) and the dump names BOTH
    deadlocked threads, their stacks, and the DiagnosedLock holder
    table."""
    test_file = tmp_path / "test_deliberate_deadlock.py"
    test_file.write_text(DEADLOCK_TEST, encoding="utf-8")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               DL4J_TPU_DEADLOCK_SENTINEL="1",
               DL4J_TPU_SENTINEL_TIMEOUT="4")
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-s",
         "-p", "deeplearning4j_tpu.util.sentinel",
         "-p", "no:cacheprovider", str(test_file)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    elapsed = time.monotonic() - t0
    out = r.stdout + r.stderr
    assert r.returncode == 3, f"rc={r.returncode}\n{out[-4000:]}"
    assert elapsed < 240, "sentinel did not fire promptly"
    assert "deadlock sentinel" in out
    # the holder table names both locks and both holder threads
    assert "sentinel_fixture.A" in out and "sentinel_fixture.B" in out
    assert "deadlock-one" in out and "deadlock-two" in out
    assert "held by" in out
    # both stacks are present, pointing into the fixture's waiters
    assert out.count("test_deliberate_deadlock.py") >= 2
    assert "end sentinel dump" in out


def test_sentinel_env_kill_switch_contract():
    """DL4J_TPU_DEADLOCK_SENTINEL follows the =='0'-only-disables
    contract (util/env.py): unset/''/true/'2' keep it armed."""
    from deeplearning4j_tpu.util import sentinel
    from deeplearning4j_tpu.util.env import scoped
    for val, want in ((None, True), ("", True), ("1", True),
                      ("true", True), ("2", True), ("0", False)):
        with scoped("DL4J_TPU_DEADLOCK_SENTINEL", val):
            assert sentinel._enabled() is want, (val, want)
    with scoped("DL4J_TPU_SENTINEL_TIMEOUT", "17.5"):
        assert sentinel._timeout_s() == 17.5
