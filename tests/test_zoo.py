"""Model zoo tests (DL4J deeplearning4j-zoo/src/test TestModels analog):
every zoo architecture builds, serializes its config round-trip, and the
small ones run a forward pass + one training step."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.models import (
    AlexNet, Darknet19, GoogLeNet, LeNet, ResNet50, SimpleCNN,
    TextGenerationLSTM, TinyYOLO, UNet, VGG16, VGG19, YOLO2,
    InceptionResNetV1, FaceNetNN4Small2,
)
from deeplearning4j_tpu.nn.conf.network import (
    ComputationGraphConfiguration, MultiLayerConfiguration,
)

ALL_MODELS = [
    LeNet(), SimpleCNN(), AlexNet(), VGG16(), VGG19(), ResNet50(),
    GoogLeNet(), Darknet19(), TinyYOLO(), YOLO2(), TextGenerationLSTM(),
    InceptionResNetV1(), FaceNetNN4Small2(), UNet(),
]


@pytest.mark.parametrize("model", ALL_MODELS, ids=lambda m: type(m).__name__)
def test_conf_builds_and_roundtrips(model):
    conf = model.conf()
    js = conf.to_json()
    if isinstance(conf, ComputationGraphConfiguration):
        conf2 = ComputationGraphConfiguration.from_json(js)
    else:
        conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.to_json() == js


def test_lenet_forward_and_fit():
    net = LeNet().init()
    x = np.random.RandomState(0).rand(4, 28, 28, 1).astype("float32")
    y = np.eye(10, dtype="float32")[np.random.RandomState(1).randint(0, 10, 4)]
    out = np.asarray(net.output(x))
    assert out.shape == (4, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-4)
    net.fit((x, y), epochs=1, batch_size=4)
    assert np.isfinite(net.score())


def test_simplecnn_forward():
    m = SimpleCNN(input_shape=(32, 32, 3))
    net = m.init()
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype("float32")
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)


def test_darknet19_small_input_forward():
    m = Darknet19(num_classes=12, input_shape=(64, 64, 3))
    net = m.init()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype("float32")
    out = np.asarray(net.output(x))
    assert out.shape == (2, 12)


def test_tinyyolo_small_forward_and_loss():
    m = TinyYOLO(num_classes=3, input_shape=(64, 64, 3))
    net = m.init()
    x = np.random.RandomState(0).rand(2, 64, 64, 3).astype("float32")
    out = np.asarray(net.output(x))
    # 64/32 = 2x2 grid, 5 anchors * (5 + 3 classes)
    assert out.shape == (2, 2, 2, 5 * 8)
    # one train step with a single labeled box
    labels = np.zeros((2, 2, 2, 4 + 3), "float32")
    labels[0, 0, 0] = [0.1, 0.2, 0.9, 1.1, 1, 0, 0]
    from deeplearning4j_tpu.data.dataset import DataSet
    net.fit(DataSet(x, labels))
    assert np.isfinite(net.score())


def test_textgen_lstm_fit():
    m = TextGenerationLSTM(total_unique_characters=12, max_length=16, units=8)
    net = m.init()
    rs = np.random.RandomState(0)
    x = np.eye(12, dtype="float32")[rs.randint(0, 12, (2, 16))]
    y = np.eye(12, dtype="float32")[rs.randint(0, 12, (2, 16))]
    net.fit((x, y), epochs=1, batch_size=2)
    assert np.isfinite(net.score())


def test_resnet50_init_params():
    """ResNet-50 initializes with the canonical parameter count (~25.6M)."""
    m = ResNet50(num_classes=1000, input_shape=(64, 64, 3))
    net = m.init()
    n = net.num_params()
    assert 25.4e6 < n < 25.8e6, n


def test_yolo_loss_prefers_accurate_boxes():
    """The rewritten YOLOv2 loss must score a well-aimed prediction lower
    than a badly-aimed one (IOU uses true predicted/label corners)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
    layer = Yolo2OutputLayer(anchors=((1.0, 1.0), (2.0, 2.0)), n_classes=2)
    h = w = 2
    labels = np.zeros((1, h, w, 4 + 2), "float32")
    labels[0, 0, 0] = [0.0, 0.0, 1.0, 1.0, 1, 0]   # unit box in cell (0,0), class 0
    good = np.zeros((1, h, w, 2 * 7), "float32")
    good[0, 0, 0, 0:2] = 0.0      # sigmoid(0)=0.5 -> center of cell
    good[0, 0, 0, 2:4] = 0.0      # wh = anchor(1,1)*exp(0) = 1x1 (exact)
    good[0, 0, 0, 4] = 4.0        # high confidence
    good[0, 0, 0, 5] = 4.0        # class 0 logit
    bad = good.copy()
    bad[0, 0, 0, 2:4] = 2.0       # wh = e^2 ~ 7.4x too large
    bad[0, 0, 0, 5:7] = [0.0, 4.0]  # wrong class
    l_good = float(layer.score(None, jnp.asarray(good), jnp.asarray(labels)))
    l_bad = float(layer.score(None, jnp.asarray(bad), jnp.asarray(labels)))
    assert l_good < l_bad, (l_good, l_bad)


class TestPretrainedFixtures:
    """ZooModel.init_pretrained drive (ZooModel.java initPretrained): the
    committed golden checkpoints under tests/fixtures/pretrained stand in
    for the reference's downloaded weight archives (no egress)."""

    FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                            "pretrained")

    def test_lenet_pretrained_accuracy_regression(self):
        from sklearn.datasets import load_digits
        from deeplearning4j_tpu.models.zoo import LeNet
        net = LeNet().init_pretrained(cache_dir=self.FIXTURES)
        d = load_digits()
        X8 = d.images.astype("float32") / 16.0
        X24 = np.repeat(np.repeat(X8, 3, axis=1), 3, axis=2)
        X = np.pad(X24, ((0, 0), (2, 2), (2, 2)))[..., None]
        Y = np.eye(10, dtype="float32")[d.target]
        ev = net.evaluate((X[1500:], Y[1500:]), batch_size=99)
        assert ev.accuracy() > 0.9      # golden fixture trained to 0.926

    def test_textgeneration_lstm_pretrained_regression(self):
        from deeplearning4j_tpu.models.zoo import TextGenerationLSTM
        net = TextGenerationLSTM(
            total_unique_characters=12, max_length=20,
            units=32).init_pretrained(cache_dir=self.FIXTURES)
        seqs = np.array([(s + np.arange(21)) % 12 for s in range(12)])
        X = np.eye(12, dtype="float32")[seqs[:, :-1]]
        out = np.asarray(net.output(X))
        acc = (out.argmax(-1) == seqs[:, 1:]).mean()
        assert acc > 0.95               # golden fixture trained to 0.997

    def test_missing_cache_raises_clear_error(self, tmp_path):
        from deeplearning4j_tpu.models.zoo import LeNet
        with pytest.raises(FileNotFoundError, match="pretrained"):
            LeNet().init_pretrained(cache_dir=str(tmp_path))


def test_resnet50_space_to_depth_stem_exact():
    """The MLPerf-style s2d stem is EXACTLY the standard stem under the
    s2d_stem_weights mapping — same conv output for the same input."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.zoo import s2d_stem_weights
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, SpaceToDepthLayer, ZeroPaddingLayer,
    )
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 64, 64, 3).astype("float32"))
    w7 = rs.randn(7, 7, 3, 16).astype("float32") * 0.1

    # standard: pad 3, 7x7 stride 2
    pad = ZeroPaddingLayer(padding=(3, 3, 3, 3))
    conv7 = ConvolutionLayer(n_out=16, kernel=(7, 7), stride=(2, 2),
                             convolution_mode="truncate", has_bias=False)
    xp, _ = pad.apply({}, {}, x)
    ref, _ = conv7.apply({"W": jnp.asarray(w7)}, {}, xp)

    # s2d: block-2, pad (2,1), 4x4 stride 1, mapped weights
    s2d = SpaceToDepthLayer(block_size=2)
    pad2 = ZeroPaddingLayer(padding=(2, 1, 2, 1))
    conv4 = ConvolutionLayer(n_out=16, kernel=(4, 4), stride=(1, 1),
                             convolution_mode="truncate", has_bias=False)
    xs, _ = s2d.apply({}, {}, x)
    xs, _ = pad2.apply({}, {}, xs)
    out, _ = conv4.apply({"W": jnp.asarray(s2d_stem_weights(w7))}, {}, xs)

    assert ref.shape == out.shape == (2, 32, 32, 16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


def test_resnet50_space_to_depth_model_trains():
    m = ResNet50(num_classes=10, input_shape=(64, 64, 3),
                 space_to_depth_stem=True)
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    net = ComputationGraph(m.conf()).init()
    rs = np.random.RandomState(1)
    X = rs.rand(4, 64, 64, 3).astype("float32")
    Y = np.eye(10, dtype="float32")[rs.randint(0, 10, 4)]
    net.fit((X, Y), epochs=1)
    assert np.isfinite(net._score)
    # same downstream trunk: parameter count differs only by the stem
    # conv (7*7*3 -> 4*4*12 rows = 192 vs 147 per filter)
    base = ComputationGraph(ResNet50(num_classes=10,
                                     input_shape=(64, 64, 3)).conf()).init()
    assert net.num_params() - base.num_params() == (192 - 147) * 64
