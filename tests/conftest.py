"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

This mirrors the reference's strategy of testing distributed code without a
real cluster (SURVEY.md §4: Spark local[N] masters) — multi-chip sharding
logic runs on 8 virtual CPU devices; the driver separately dry-runs the
multi-chip path, and bench.py runs on real TPU.

Markers (README "Running the tests"):
- `slow`: tests that individually take >=7s on an 8-vCPU box (big jit
  compiles: pipeline/context parallel, f64 gradcheck matrices, zoo
  forwards, multi-OS-process runs). `pytest -m "not slow"` is the quick
  gate; the full suite is the merge gate.
- `distributed`: tests that spawn real extra OS processes.

A persistent XLA compilation cache (JAX_TEST_CACHE_DIR, default
<repo>/.jaxcache, gitignored) makes repeat runs compile-free: the first
run pays the jit cost, later runs reload compiled programs from disk.
"""
import os
import sys

# allow invoking pytest from inside tests/ (package not pip-installed)
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# XLA's cpu_aot_loader logs an E-level "could lead to SIGILL" wall of
# text for every compile-cache hit whose recorded machine-feature string
# differs textually from the host's (the compile side records XLA tuning
# pseudo-features like +prefer-no-scatter that host detection never
# lists — same box, pure noise). Real failures surface as Python
# exceptions, so silence C++ glog in tests unless the caller overrides.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax  # noqa: E402
import pytest  # noqa: E402

# The TPU plugin ("axon") force-appends itself to jax_platforms at import,
# overriding the env var — pin the config back to CPU-only for tests.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent compile cache: repeat suite runs skip XLA compilation.
# ONE path definition (bench.cache_dir) shared with bench.py and
# __graft_entry__ so the caches can't silently split.
from bench import cache_dir  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_TEST_CACHE_DIR", cache_dir()))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


# tests that individually take >=7s on the 8-vCPU reference box (measured
# via --durations: big pipeline/ring-attention compiles, f64 gradchecks,
# zoo forwards, multi-process distributed runs) — names without any
# parametrize suffix, so every variant of a listed test is marked
_SLOW = {
    "tests/test_tpu_lowering.py::TestFlashKernelLowering::test_backward_kernels_with_lse_cotangent",
    "tests/test_tpu_lowering.py::TestFlashKernelLowering::test_cross_attention_shapes",
    "tests/test_tpu_lowering.py::TestRingFlashLowering::test_ring_flash_over_seq_mesh",
    "tests/test_tpu_lowering.py::TestFlagshipLowering::test_graft_entry_forward_lowers_for_tpu",
    "tests/test_tpu_lowering.py::TestFlagshipLowering::test_resnet_train_step_lowers_for_tpu",
    "tests/test_attention.py::test_context_parallel_dp_sp_mesh_trains",
    "tests/test_attention.py::test_context_parallel_graph_matches_single_device",
    "tests/test_attention.py::test_context_parallel_honors_label_mask",
    "tests/test_attention.py::test_context_parallel_masked_matches_single_device",
    "tests/test_attention.py::test_context_parallel_step_matches_single_device",
    "tests/test_attention.py::test_pipeline_parallel_honors_masks",
    "tests/test_attention.py::test_pipeline_parallel_step_matches_single_device",
    "tests/test_attention.py::test_pipeline_parallel_trains",
    "tests/test_attention.py::test_ring_attention_masked_matches_dense",
    "tests/test_attention.py::test_ring_attention_matches_dense",
    "tests/test_attention.py::test_transformer_block_and_moe_shapes",
    "tests/test_attention.py::test_transformer_lm_trains",
    "tests/test_attention.py::test_transformer_tp_sharded_step",
    "tests/test_gradientcheck.py::test_gc_attention_dropout_fixed_rng",
    "tests/test_gradientcheck.py::test_gc_graves_bidirectional_lstm",
    "tests/test_gradientcheck.py::test_gc_graves_lstm",
    "tests/test_gradientcheck.py::test_gc_lstm_last_time_step_global_pool",
    "tests/test_gradientcheck.py::test_gc_ring_attention_fd",
    "tests/test_gradientcheck.py::test_gc_separable_conv",
    "tests/test_gradientcheck.py::test_gc_transformer_block_blockwise",
    "tests/test_gradientcheck.py::test_gc_vae_pretrain_elbo",
    "tests/test_gradientcheck.py::test_gc_vae_supervised",
    "tests/test_gradientcheck.py::test_gc_yolo_loss",
    "tests/test_keras_import.py::test_separable_and_depthwise_conv_parity",
    "tests/test_keras_import.py::test_sequential_cnn_parity",
    "tests/test_memory.py::test_memory_report_graph",
    "tests/test_nlp.py::test_paragraph_vectors_labels",
    "tests/test_nlp.py::test_spark_word2vec_partition_parallel",
    "tests/test_nlp.py::test_word2vec_cbow_and_hs",
    "tests/test_nlp.py::test_word2vec_separates_topics",
    "tests/test_parallel.py::test_shared_gradients_two_os_processes_over_socket_transport",
    "tests/test_parallel.py::test_two_process_checkpoint_crash_resume_matches_uninterrupted",
    "tests/test_parallel.py::test_two_process_jax_distributed_parallel_wrapper",
    "tests/test_pretraining.py::test_vae_pretrain_via_driver",
    "tests/test_regularization.py::test_dropout_variants_train_only_and_nets_train",
    "tests/test_server_cli.py::test_cli_trains_and_saves",
    "tests/test_solvers.py::test_lbfgs_beats_gradient_descent_iterations",
    "tests/test_zoo.py::test_darknet19_small_input_forward",
    "tests/test_zoo.py::test_simplecnn_forward",
    "tests/test_zoo.py::test_tinyyolo_small_forward_and_loss",
}

_DISTRIBUTED = {
    "tests/test_parallel.py::test_shared_gradients_two_os_processes_over_socket_transport",
    "tests/test_parallel.py::test_two_process_checkpoint_crash_resume_matches_uninterrupted",
    "tests/test_parallel.py::test_two_process_jax_distributed_parallel_wrapper",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: >=7s on the 8-vCPU box; excluded by -m 'not slow'")
    config.addinivalue_line(
        "markers", "distributed: spawns extra OS processes")


def pytest_collection_modifyitems(config, items):
    for item in items:
        # normalize to the repo-relative "tests/file.py::name" form so the
        # match is independent of the invocation directory/rootdir
        base = "tests/" + item.path.name + "::" + \
            item.nodeid.split("::", 1)[-1].split("[")[0]
        if base in _SLOW:
            item.add_marker(pytest.mark.slow)
        if base in _DISTRIBUTED:
            item.add_marker(pytest.mark.distributed)


# ------------------------------------------------------ deadlock sentinel
# A wedged test used to be a MUTE hang: the tier-1 `timeout` kill left
# no evidence of who held what. Importing the hook arms the sentinel
# (util/sentinel.py): per-test wall-time watchdog that dumps every
# thread's stack + the DiagnosedLock holder table, then exits 3.
# Knobs: DL4J_TPU_DEADLOCK_SENTINEL (only "0" disables),
# DL4J_TPU_SENTINEL_TIMEOUT (seconds, default 300).
from deeplearning4j_tpu.util.sentinel import (  # noqa: E402,F401
    pytest_runtest_protocol,
)
