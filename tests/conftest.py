"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

This mirrors the reference's strategy of testing distributed code without a
real cluster (SURVEY.md §4: Spark local[N] masters) — multi-chip sharding
logic runs on 8 virtual CPU devices; the driver separately dry-runs the
multi-chip path, and bench.py runs on real TPU.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The TPU plugin ("axon") force-appends itself to jax_platforms at import,
# overriding the env var — pin the config back to CPU-only for tests.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
