"""Pallas flash-attention kernel vs the XLA reference implementation
(interpret mode on CPU; the same kernel compiles for TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.ops import flash_attention


def _qkv(b=2, t=48, h=4, d=16, seed=0, dtype="float32"):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(b, t, h, d).astype(dtype))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_key_mask_and_fully_masked_rows():
    q, k, v = _qkv(seed=1)
    mask = np.ones((2, 48), np.float32)
    mask[0, 20:] = 0.0
    mask[1, :] = 0.0                     # batch 1 fully masked -> zeros
    ref = dot_product_attention(q, k, v, mask=jnp.asarray(mask))
    out = flash_attention(q, k, v, mask=jnp.asarray(mask),
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert np.abs(np.asarray(out)[1]).max() == 0.0


def test_flash_ragged_length_padding():
    q, k, v = _qkv(t=50, seed=2)         # 50 % 16 != 0 -> internal pad
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_bf16_accumulates_in_f32():
    q, k, v = _qkv(seed=3, dtype="float32")
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = dot_product_attention(qb, kb, vb, causal=True)
    out = flash_attention(qb, kb, vb, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_flash_gradients_match_dense():
    q, k, v = _qkv(t=32, seed=4)
    mask = jnp.asarray((np.random.RandomState(5).rand(2, 32) > 0.2)
                       .astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask=mask, causal=True,
                                       block_q=16, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, mask=mask,
                                             causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_mha_flash_impl_matches_dense_and_trains():
    """MultiHeadAttention(attention_impl='flash') end-to-end parity + a
    training step through the custom VJP."""
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention
    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(2, 24, 32).astype("float32"))
    mask = jnp.asarray((rs.rand(2, 24) > 0.2).astype("float32"))
    dense = MultiHeadAttention(n_out=32, n_heads=4, causal=True)
    flash = MultiHeadAttention(n_out=32, n_heads=4, causal=True,
                               attention_impl="flash", block_size=8)
    params, state = dense.init(jax.random.PRNGKey(0),
                               InputType.recurrent(32, 24))
    yd, _ = dense.apply(params, state, x, mask=mask)
    yf, _ = flash.apply(params, state, x, mask=mask)
    np.testing.assert_allclose(np.asarray(yf), np.asarray(yd),
                               atol=3e-5, rtol=3e-5)

    def loss(p, layer):
        y, _ = layer.apply(p, state, x, mask=mask)
        return jnp.sum(y ** 2)

    gd = jax.grad(loss)(params, dense)
    gf = jax.grad(loss)(params, flash)
    for key in params:
        np.testing.assert_allclose(np.asarray(gf[key]), np.asarray(gd[key]),
                                   atol=2e-4, rtol=2e-4, err_msg=key)


def test_flash_cross_attention_gradients():
    """tq != tk (cross-attention): the Pallas backward has no square
    assumption — gradients must match the dense reference."""
    rs = np.random.RandomState(8)
    q = jnp.asarray(rs.randn(2, 24, 4, 16).astype("float32"))
    k = jnp.asarray(rs.randn(2, 40, 4, 16).astype("float32"))
    v = jnp.asarray(rs.randn(2, 40, 4, 16).astype("float32"))
    mask = jnp.asarray((rs.rand(2, 40) > 0.2).astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask=mask,
                                       block_q=8, block_k=8) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, mask=mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)
    # unequal q/k block sizes are legal (no square assumption anywhere)
    out_uneq = flash_attention(q, k, v, mask=mask, block_q=8, block_k=20)
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out_uneq), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_bf16_gradients_finite_and_close():
    rs = np.random.RandomState(9)
    mk = lambda: jnp.asarray(rs.randn(2, 16, 2, 8), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=8,
                                       block_k=8).astype(jnp.float32) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a in g:
        assert a.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(a, np.float32)).all()


def test_flash_lse_shard_merge_identity():
    """return_lse enables exact cross-shard composition: flash over two
    key shards merged via the LSE rule == flash over the full keys —
    the building block ring/context parallelism uses across chips."""
    rs = np.random.RandomState(10)
    q = jnp.asarray(rs.randn(2, 16, 2, 8).astype("float32"))
    k = jnp.asarray(rs.randn(2, 32, 2, 8).astype("float32"))
    v = jnp.asarray(rs.randn(2, 32, 2, 8).astype("float32"))
    full = flash_attention(q, k, v, block_q=8, block_k=8)

    o1, l1 = flash_attention(q, k[:, :16], v[:, :16], block_q=8,
                             block_k=8, return_lse=True)
    o2, l2 = flash_attention(q, k[:, 16:], v[:, 16:], block_q=8,
                             block_k=8, return_lse=True)
    m = jnp.maximum(l1, l2)
    w1 = jnp.exp(l1 - m)[..., None]
    w2 = jnp.exp(l2 - m)[..., None]
    merged = (w1 * o1 + w2 * o2) / (w1 + w2)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(full),
                               atol=2e-5, rtol=2e-5)


def test_flash_lse_merge_trains_correctly():
    """Gradients THROUGH the two-shard LSE merge must equal gradients of
    full attention — the property that makes a flash-per-shard ring
    trainable with plain autodiff."""
    rs = np.random.RandomState(11)
    q = jnp.asarray(rs.randn(2, 8, 2, 8).astype("float32"))
    k = jnp.asarray(rs.randn(2, 16, 2, 8).astype("float32"))
    v = jnp.asarray(rs.randn(2, 16, 2, 8).astype("float32"))

    def loss_merged(q, k, v):
        o1, l1 = flash_attention(q, k[:, :8], v[:, :8], block_q=8,
                                 block_k=8, return_lse=True)
        o2, l2 = flash_attention(q, k[:, 8:], v[:, 8:], block_q=8,
                                 block_k=8, return_lse=True)
        m = jnp.maximum(l1, l2)
        w1 = jnp.exp(l1 - m)[..., None]
        w2 = jnp.exp(l2 - m)[..., None]
        return jnp.sum(((w1 * o1 + w2 * o2) / (w1 + w2)) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v) ** 2)

    gm = jax.grad(loss_merged, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gm, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


@pytest.mark.slow
def test_ring_flash_matches_ring_online():
    """ring_flash_self_attention (fused kernel per shard + LSE merge)
    must match the lax online-softmax ring bit-for-tolerance on the
    8-device CPU mesh, causal and masked."""
    from deeplearning4j_tpu.parallel.mesh import (
        MeshConfig, build_mesh, compat_shard_map,
    )
    from deeplearning4j_tpu.parallel.ring import (
        ring_flash_self_attention, ring_self_attention,
    )
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(MeshConfig(data=2, seq=4))
    rs = np.random.RandomState(12)
    T = 32                                   # 8 per shard over seq=4
    q = jnp.asarray(rs.randn(2, T, 2, 8).astype("float32"))
    k = jnp.asarray(rs.randn(2, T, 2, 8).astype("float32"))
    v = jnp.asarray(rs.randn(2, T, 2, 8).astype("float32"))
    mask = jnp.asarray((rs.rand(2, T) > 0.2).astype("float32"))
    spec = P(None, "seq", None, None)
    mspec = P(None, "seq")

    for causal in (True, False):
        ref_f = compat_shard_map(
            lambda q, k, v, m, c=causal: ring_self_attention(
                q, k, v, axis_name="seq", causal=c, mask=m),
            mesh, (spec, spec, spec, mspec), spec)
        new_f = compat_shard_map(
            lambda q, k, v, m, c=causal: ring_flash_self_attention(
                q, k, v, axis_name="seq", causal=c, mask=m,
                block_q=8, block_k=8),
            mesh, (spec, spec, spec, mspec), spec)
        ref = np.asarray(ref_f(q, k, v, mask))
        new = np.asarray(new_f(q, k, v, mask))
        np.testing.assert_allclose(new, ref, atol=3e-5, rtol=3e-5,
                                   err_msg=f"causal={causal}")

    # gradients through the sharded flash ring match the online ring
    def loss(fn):
        def go(q, k, v):
            return jnp.sum(fn(q, k, v, mask) ** 2)
        return go

    ref_f = compat_shard_map(
        lambda q, k, v, m: ring_self_attention(
            q, k, v, axis_name="seq", causal=True, mask=m),
        mesh, (spec, spec, spec, mspec), spec)
    new_f = compat_shard_map(
        lambda q, k, v, m: ring_flash_self_attention(
            q, k, v, axis_name="seq", causal=True, mask=m,
            block_q=8, block_k=8),
        mesh, (spec, spec, spec, mspec), spec)
    gr = jax.grad(loss(ref_f), argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss(new_f), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gn, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("shape,bq,bk", [
    ((1, 96, 2, 128), 32, 64),      # head_dim 128, uneven T vs blocks
    ((2, 40, 1, 256), 16, 16),      # head_dim 256 (VMEM-heavy on TPU)
    ((1, 130, 2, 64), 128, 128),    # T barely over one block
    ((1, 8, 1, 32), 128, 128),      # T far below the block size
])
def test_flash_block_size_shape_matrix(shape, bq, bk):
    """First-contact de-risking: the kernel must be exact across the
    block-size x head-dim x ragged-T matrix that real models hit (the
    same configs the DL4J_TPU_FLASH_BLOCK_Q/K knobs select on
    hardware)."""
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
    from deeplearning4j_tpu.ops import flash_attention

    rs = np.random.RandomState(42)
    b, t, h, d = shape
    q, k, v = [jnp.asarray(rs.randn(b, t, h, d).astype("float32") * 0.3)
               for _ in range(3)]
    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal, block_q=bq,
                              block_k=bk, interpret=True)
        ref = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_flash_env_block_override(monkeypatch):
    """The env knobs must actually reach the kernel — including overriding
    EXPLICIT caller block sizes (they are the no-code-edit recovery path
    on hardware, and layers pass their configured block_size)."""
    from deeplearning4j_tpu.ops import flash_attention

    rs = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rs.randn(1, 64, 1, 32).astype("float32"))
               for _ in range(3)]
    base = np.asarray(flash_attention(q, k, v, interpret=True))
    monkeypatch.setenv("DL4J_TPU_FLASH_BLOCK_Q", "16")
    monkeypatch.setenv("DL4J_TPU_FLASH_BLOCK_K", "32")
    tuned = np.asarray(flash_attention(q, k, v, block_q=128, block_k=128,
                                       interpret=True))
    np.testing.assert_allclose(tuned, base, rtol=1e-5, atol=1e-5)
    # the override is observably live: garbage must raise, not be ignored
    monkeypatch.setenv("DL4J_TPU_FLASH_BLOCK_Q", "not-a-number")
    with pytest.raises(ValueError):
        flash_attention(q, k, v, interpret=True)
