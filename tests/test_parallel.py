"""Parallelism tests on the 8-device virtual CPU mesh (conftest.py), the
analog of DL4J's local[N]-master Spark tests and ParallelWrapper tests
(SURVEY.md §4: distributed tests without a real cluster)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import (
    EncodingHandler, MeshConfig, ParallelInference, ParallelWrapper,
    ShardingRules, TrainingMode, build_mesh, shard_params,
    threshold_decode, threshold_encode,
)
from deeplearning4j_tpu.parallel.encoding import bitmap_decode, bitmap_encode
from deeplearning4j_tpu.parallel.inference import InferenceMode


def _blob_data(n=320, d=8, k=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    X = np.concatenate([centers[i] + rs.randn(n // k, d)
                        for i in range(k)]).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    perm = rs.permutation(n)
    return X[perm], Y[perm]


def _mlp(seed=7, lr=5e-2):
    return (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())


def test_mesh_builds_8_devices():
    mesh = build_mesh(MeshConfig())
    assert mesh.shape["data"] == 8
    mesh2 = build_mesh(MeshConfig(data=2, model=2, seq=2))
    assert (mesh2.shape["data"], mesh2.shape["model"], mesh2.shape["seq"]) \
        == (2, 2, 2)
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(data=3))


def test_sync_gradients_trains():
    X, Y = _blob_data()
    net = MultiLayerNetwork(_mlp()).init()
    w = ParallelWrapper(net, mode=TrainingMode.SYNC_GRADIENTS)
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=8)
    acc = net.evaluate((X, Y)).accuracy()
    assert acc > 0.9, acc


def test_sync_matches_single_device_step():
    """One sync-DP step over 8 shards == one single-device step on the same
    global batch (SPMD is semantics-preserving)."""
    X, Y = _blob_data(n=64)
    net_a = MultiLayerNetwork(_mlp(seed=3, lr=1e-2)).init()
    net_b = MultiLayerNetwork(_mlp(seed=3, lr=1e-2)).init()
    # single device step
    net_b.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    # parallel step
    w = ParallelWrapper(net_a, mode=TrainingMode.SYNC_GRADIENTS)
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    fa = np.asarray(net_a.params_flat())
    fb = np.asarray(net_b.params_flat())
    np.testing.assert_allclose(fa, fb, atol=1e-5)


def test_averaging_mode_trains_and_averages():
    X, Y = _blob_data()
    net = MultiLayerNetwork(_mlp()).init()
    w = ParallelWrapper(net, mode=TrainingMode.AVERAGING,
                        averaging_frequency=2)
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=8)
    acc = net.evaluate((X, Y)).accuracy()
    assert acc > 0.9, acc
    # after fit, all stacked replicas hold identical (averaged) params
    sp, _, _ = w._stacked
    leaf = jax.tree_util.tree_leaves(sp)[0]
    np.testing.assert_allclose(np.asarray(leaf[0]), np.asarray(leaf[-1]),
                               atol=1e-6)


def test_averaging_freq1_close_to_sync():
    """AVERAGING with frequency=1 should track sync-DP closely (same data
    order, same seed): parameters equal after each averaged step for SGD."""
    X, Y = _blob_data(n=128)
    # use plain SGD so averaging params == averaging gradients exactly
    conf = (NeuralNetConfiguration.Builder()
            .seed(5).updater(Sgd(1e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net_a = MultiLayerNetwork(conf).init()
    net_s = MultiLayerNetwork(conf).init()
    wa = ParallelWrapper(net_a, mode=TrainingMode.AVERAGING,
                         averaging_frequency=1)
    wa.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    ws = ParallelWrapper(net_s, mode=TrainingMode.SYNC_GRADIENTS)
    ws.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_s.params_flat()), atol=1e-5)


def test_parallel_inference_sequential_and_batched():
    X, Y = _blob_data(n=64)
    net = MultiLayerNetwork(_mlp()).init()
    expected = np.asarray(net.output(X[:10]))
    pi = ParallelInference(net, mode=InferenceMode.SEQUENTIAL)
    np.testing.assert_allclose(np.asarray(pi.output(X[:10])), expected,
                               atol=1e-5)
    with ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_batch_size=32) as pib:
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(8) as ex:
            futs = [ex.submit(pib.output, X[i:i + 5]) for i in range(0, 40, 5)]
            outs = [f.result(timeout=60) for f in futs]
    got = np.concatenate(outs)
    ref = np.asarray(net.output(X[:40]))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_parallel_inference_odd_batch_padding():
    X, _ = _blob_data(n=64)
    net = MultiLayerNetwork(_mlp()).init()
    pi = ParallelInference(net, mode=InferenceMode.SEQUENTIAL)
    out = pi.output(X[:13])           # 13 not divisible by 8 -> padded
    assert out.shape == (13, 4)
    np.testing.assert_allclose(out, np.asarray(net.output(X[:13])), atol=1e-5)


def test_parallel_inference_update_model_swaps_compiled_fn():
    # update_model must re-jit: the old compiled graph closed over the old
    # model's forward; after a swap, outputs must come from the NEW model
    X, _ = _blob_data(n=16)
    net_a = MultiLayerNetwork(_mlp()).init()
    net_b = MultiLayerNetwork(_mlp()).init()
    with ParallelInference(net_a, mode=InferenceMode.BATCHED) as pi:
        np.testing.assert_allclose(np.asarray(pi.output(X[:8])),
                                   np.asarray(net_a.output(X[:8])), atol=1e-5)
        pi.update_model(net_b)
        np.testing.assert_allclose(np.asarray(pi.output(X[:8])),
                                   np.asarray(net_b.output(X[:8])), atol=1e-5)


def test_parallel_inference_rejects_after_shutdown():
    X, _ = _blob_data(n=16)
    net = MultiLayerNetwork(_mlp()).init()
    pi = ParallelInference(net, mode=InferenceMode.BATCHED)
    pi.output(X[:8])
    pi.shutdown()
    with pytest.raises(RuntimeError):
        pi.output(X[:8])


def test_shared_gradients_trainer_converges_like_dense_sync():
    """The encoded cross-pod trainer (threshold encode + residual carry +
    host-side exchange) must track the dense-sync loss curve within
    tolerance — the convergence contract of SharedTrainingMaster /
    WiredEncodingHandler."""
    from deeplearning4j_tpu.parallel import SharedGradientsTrainer
    from deeplearning4j_tpu.train.listeners import (
        CollectScoresIterationListener,
    )
    X, Y = _blob_data(n=256)

    def make_net():
        conf = (NeuralNetConfiguration.Builder()
                .seed(9).updater(Sgd(5e-2)).list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    dense = make_net()
    dense_scores = CollectScoresIterationListener()
    dense.set_listeners(dense_scores)
    ParallelWrapper(dense, mode=TrainingMode.SYNC_GRADIENTS).fit(
        ArrayDataSetIterator(X, Y, batch_size=64), epochs=6)

    enc = make_net()
    enc_scores = CollectScoresIterationListener()
    enc.set_listeners(enc_scores)
    trainer = SharedGradientsTrainer(enc, n_workers=2, threshold=5e-4)
    trainer.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=6)

    d = np.array([s for _, s in dense_scores.scores])
    e = np.array([s for _, s in enc_scores.scores])
    assert len(d) == len(e) == 24
    # both must learn, and the curves must agree within tolerance
    assert e[-1] < 0.75 * e[0], (e[0], e[-1])
    np.testing.assert_allclose(e, d, atol=0.15)
    # the exchange must actually be sparse/compressed
    assert trainer.sparsity() < 0.5
    assert trainer.compression_ratio() < 0.5
    assert trainer.transport.messages_sent == 24 * 2


def test_shared_gradients_residual_carry_transmits_small_grads():
    """Sub-threshold gradient mass must eventually be transmitted via the
    residual accumulator, not lost (EncodingHandler left-overs)."""
    from deeplearning4j_tpu.parallel import SharedGradientsTrainer
    X, Y = _blob_data(n=128)
    conf = (NeuralNetConfiguration.Builder()
            .seed(2).updater(Sgd(1e-2)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    # threshold far above typical grad magnitude: single-shot encoding would
    # send nothing, only residual accumulation gets updates through
    trainer = SharedGradientsTrainer(net, n_workers=2, threshold=5e-2)
    w_before = np.asarray(net.params["0"]["W"]).copy()
    trainer.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=20)
    moved = np.abs(np.asarray(net.params["0"]["W"]) - w_before).max()
    assert moved > 1e-3, moved
    assert np.isfinite(net.score())


def test_shared_gradients_two_os_processes_over_socket_transport():
    """The DCN path for real: two OS processes (one per logical pod)
    exchange encoded-gradient messages over TCP (SocketTransport) and must
    (a) both converge and (b) end with identical replicas — the lockstep
    property the reference's accumulator design relies on
    (SilentTrainingDriver.java:112-121)."""
    import socket
    import subprocess
    import sys
    import tempfile

    # find a free consecutive port pair
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base_port = s.getsockname()[1]

    script = os.path.join(os.path.dirname(__file__), "_shared_worker.py")
    with tempfile.TemporaryDirectory() as td:
        outs = [os.path.join(td, f"w{r}.npz") for r in range(2)]
        procs = [subprocess.Popen(
            [sys.executable, script, str(r), "2", str(base_port), outs[r]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for r in range(2)]
        for p in procs:
            out, _ = p.communicate(timeout=300)
            assert p.returncode == 0, out.decode()[-2000:]
        w0, w1 = (np.load(o) for o in outs)
        # replicas in lockstep: same params after 24 iterations
        np.testing.assert_allclose(w0["params"], w1["params"], atol=1e-5)
        # both learned on their own shards
        for w in (w0, w1):
            scores = w["scores"]
            assert len(scores) == 24
            assert scores[-1] < 0.75 * scores[0], scores
            assert w["accuracy"] > 0.85, w["accuracy"]
            assert w["messages_sent"] == 24


def test_ragged_final_batch_wrap_pads():
    """100 samples, batch 64 on 8 workers: final batch of 36 trains via
    wrap-padding instead of crashing (DL4J handles ragged batches too)."""
    X, Y = _blob_data(n=320)
    net = MultiLayerNetwork(_mlp()).init()
    w = ParallelWrapper(net, mode=TrainingMode.SYNC_GRADIENTS)
    w.fit(ArrayDataSetIterator(X[:100], Y[:100], batch_size=64), epochs=2)
    assert np.isfinite(net.score())
    net2 = MultiLayerNetwork(_mlp()).init()
    w2 = ParallelWrapper(net2, mode=TrainingMode.AVERAGING,
                         averaging_frequency=2)
    w2.fit(ArrayDataSetIterator(X[:100], Y[:100], batch_size=64), epochs=2)
    assert np.isfinite(net2.score())


def test_shard_params_preserves_empty_layers():
    from deeplearning4j_tpu.nn.layers import ActivationLayer
    import jax
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="identity"))
            .layer(ActivationLayer(activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    mesh = build_mesh(MeshConfig())
    placed = shard_params(net.params, mesh)
    assert (jax.tree_util.tree_structure(placed) ==
            jax.tree_util.tree_structure(net.params))
    assert placed["1"] == {}


# ---------------------------------------------------------------- encoding
def test_threshold_encode_roundtrip():
    rs = np.random.RandomState(0)
    g = rs.randn(1000).astype("float32") * 0.01
    g[::50] = 0.5          # 20 big elements
    idx, signs, residual = threshold_encode(jnp.asarray(g), 0.1)
    dec = threshold_decode(idx, signs, 0.1, (1000,))
    dec = np.asarray(dec)
    # decoded + residual == original
    np.testing.assert_allclose(dec + np.asarray(residual), g, atol=1e-6)
    assert (np.asarray(idx) >= 0).sum() == 20
    assert np.all(dec[::50] == 0.1)


def test_bitmap_encode_roundtrip():
    rs = np.random.RandomState(1)
    g = rs.randn(100).astype("float32")
    packed, residual = bitmap_encode(jnp.asarray(g), 0.5)
    dec = np.asarray(bitmap_decode(packed, 0.5, (100,)))
    np.testing.assert_allclose(dec + np.asarray(residual), g, atol=1e-6)
    assert set(np.unique(dec)).issubset({-0.5, 0.0, 0.5})


def test_encoding_handler_residual_accumulates():
    h = EncodingHandler(threshold=0.1, boundary=0.5)
    g = np.full(100, 0.06, "float32")        # below threshold
    idx, _, _ = h.encode(g)
    assert (np.asarray(idx) >= 0).sum() == 0   # nothing sent
    idx, signs, thr = h.encode(g)              # residual pushes over
    assert (np.asarray(idx) >= 0).sum() == 100


# ---------------------------------------------------------------- sharding
def test_shard_params_megatron_rule():
    mesh = build_mesh(MeshConfig(data=4, model=2))
    net = MultiLayerNetwork(_mlp()).init()
    rules = ShardingRules.megatron()
    placed = shard_params(net.params, mesh, rules)
    W = placed["0"]["W"]
    spec = W.sharding.spec
    assert tuple(spec) == (None, "model"), spec
    b = placed["0"]["b"]
    assert tuple(b.sharding.spec) == (), b.sharding.spec


def _run_two_process_cluster(script, outs, env_extra=None, timeout=300):
    """Spawn a 2-process jax.distributed cluster on a fresh port and wait
    for both workers (shared by the distributed tests)."""
    import socket
    import subprocess
    import sys
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    procs = [subprocess.Popen(
        [sys.executable, script, str(r), "2", str(port), outs[r]],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(2)]
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, out.decode()[-2000:]


def test_two_process_jax_distributed_parallel_wrapper():
    """A REAL multi-host exercise (round-2 VERDICT item 8): two OS
    processes jax.distributed.initialize over localhost, each contributing
    4 CPU devices; ParallelWrapper sync-DP runs over the GLOBAL 8-device
    mesh (gradient all-reduce crosses the process boundary via Gloo) and
    both replicas converge to identical parameters."""
    import tempfile

    script = os.path.join(os.path.dirname(__file__),
                          "_distributed_worker.py")
    with tempfile.TemporaryDirectory() as td:
        outs = [os.path.join(td, f"w{r}.npz") for r in range(2)]
        _run_two_process_cluster(script, outs)
        w0, w1 = (np.load(o) for o in outs)
        assert int(w0["process_count"]) == 2
        assert int(w0["device_count"]) == 8
        np.testing.assert_allclose(w0["params"], w1["params"], atol=1e-6)
        for w in (w0, w1):
            assert w["accuracy"] > 0.95, w["accuracy"]
            assert np.isfinite(w["final_score"])


def test_two_process_checkpoint_crash_resume_matches_uninterrupted():
    """Elastic recovery, multi-host (SURVEY.md §5.3: checkpoint + restart
    IS the failure story, and this exceeds the reference, which never
    tests one): a 2-process cluster trains 4 epochs, the coordinator
    checkpoints, the WHOLE cluster dies; a fresh cluster restores the zip
    and trains 4 more. Final parameters must match an uninterrupted
    8-epoch run to float precision."""
    import tempfile

    script = os.path.join(os.path.dirname(__file__),
                          "_distributed_worker.py")

    def run_cluster(phase, ckpt, outs):
        env_extra = {"DL4J_TPU_WORKER_CKPT": ckpt}
        if phase:
            env_extra["DL4J_TPU_WORKER_PHASE"] = phase
        _run_two_process_cluster(script, outs, env_extra)

    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "mid.zip")
        outs_a = [os.path.join(td, f"a{r}.npz") for r in range(2)]
        outs_b = [os.path.join(td, f"b{r}.npz") for r in range(2)]
        outs_c = [os.path.join(td, f"c{r}.npz") for r in range(2)]
        run_cluster("first", ckpt, outs_a)     # 4 epochs + checkpoint
        assert os.path.exists(ckpt)
        run_cluster("resume", ckpt, outs_b)    # new cluster, 4 more
        run_cluster("", ckpt + ".unused", outs_c)   # uninterrupted 8
        resumed = np.load(outs_b[0])["params"]
        straight = np.load(outs_c[0])["params"]
        np.testing.assert_allclose(resumed, straight, atol=1e-6)
        assert np.load(outs_b[0])["accuracy"] > 0.95


def test_shared_gradients_trainer_works_on_graphs():
    """Encoded-gradient training accepts ComputationGraphs (single-in/out),
    completing the DCN story for DAG models."""
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel import SharedGradientsTrainer
    X, Y = _blob_data(n=128)
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(3)
                      .updater(Sgd(5e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(8)))
    g.add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"), "d")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    trainer = SharedGradientsTrainer(net, n_workers=2, threshold=5e-4)
    from deeplearning4j_tpu.data.dataset import DataSet
    for _ in range(12):
        trainer.fit(DataSet(X, Y), epochs=1)
    acc = net.evaluate(DataSet(X, Y)).accuracy()
    assert acc > 0.9, acc
    assert trainer.compression_ratio() < 0.5


def test_ragged_batch_is_exact_not_double_weighted():
    """VERDICT r3 weak #4: ragged final batches must train EXACTLY like a
    single-device step on the same examples — padding rows are excluded
    via a zero labels-mask with loss renormalization, not double-counted."""
    from deeplearning4j_tpu.parallel import (
        MeshConfig, ParallelWrapper, TrainingMode, build_mesh,
    )
    X, Y = _blob_data(n=44, seed=3)      # 44 % 8 != 0 -> ragged on 8 workers
    single = MultiLayerNetwork(_mlp(seed=5)).init()
    dist = MultiLayerNetwork(_mlp(seed=5)).init()
    for k in single.params:
        for pk in single.params[k]:
            np.testing.assert_array_equal(np.asarray(single.params[k][pk]),
                                          np.asarray(dist.params[k][pk]))
    # one full-batch step each (no dropout, no BN -> deterministic)
    single.fit((X, Y), batch_size=64)
    mesh = build_mesh(MeshConfig())
    ParallelWrapper(dist, mesh=mesh, mode=TrainingMode.SYNC_GRADIENTS).fit(
        (X, Y), batch_size=64, epochs=1)
    assert abs(single.score() - dist.score()) < 1e-6
    for k in single.params:
        for pk in single.params[k]:
            np.testing.assert_allclose(
                np.asarray(single.params[k][pk]),
                np.asarray(dist.params[k][pk]),
                rtol=2e-6, atol=2e-6, err_msg=f"{k}/{pk}")


def test_averaging_listener_deferred_fetch_scores_in_order():
    """Listener callbacks in AVERAGING mode are deferred one iteration (the
    loss fetch overlaps the next dispatched step) but must deliver every
    iteration exactly once, in order, with finite per-iteration scores."""
    from deeplearning4j_tpu.train.listeners import TrainingListener

    class Capture(TrainingListener):
        def __init__(self):
            self.calls = []

        def iteration_done(self, model, iteration, epoch, score,
                           etl_ms, batch_size):
            self.calls.append((iteration, epoch, score))

    net = MultiLayerNetwork(_mlp()).init()
    cap = Capture()
    net.set_listeners(cap)
    rs = np.random.RandomState(0)
    X = rs.rand(32, 8).astype("float32")
    Y = np.eye(4, dtype="float32")[rs.randint(0, 4, 32)]
    w = ParallelWrapper(net, mode=TrainingMode.AVERAGING,
                        averaging_frequency=2)
    w.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
    its = [c[0] for c in cap.calls]
    assert its == sorted(its) and len(its) == len(set(its))
    assert len(cap.calls) == 4          # 2 batches x 2 epochs
    assert all(np.isfinite(c[2]) for c in cap.calls)
    epochs_seen = [c[1] for c in cap.calls]
    assert epochs_seen == [0, 0, 1, 1]  # flushed before epoch rollover


def test_wrapper_applies_constraints():
    """ParallelWrapper training must apply post-update parameter
    constraints (DL4J applyConstraints runs in every trainer) — sync,
    averaging, and zero-sharded paths all project after the update."""
    from deeplearning4j_tpu.nn.regularization import MaxNormConstraint

    def conf():
        return (NeuralNetConfiguration.Builder().seed(5).updater(Adam(5e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh",
                                  constraints=(MaxNormConstraint(
                                      max_norm=0.5),)))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())

    X, Y = _blob_data(n=128)
    for kwargs in ({"mode": TrainingMode.SYNC_GRADIENTS},
                   {"mode": TrainingMode.SYNC_GRADIENTS, "zero_stage": 3},
                   {"mode": TrainingMode.AVERAGING,
                    "averaging_frequency": 2}):
        net = MultiLayerNetwork(conf()).init()
        ParallelWrapper(net, **kwargs).fit(
            ArrayDataSetIterator(X, Y, batch_size=64), epochs=4)
        W = np.asarray(net.params["0"]["W"])
        norms = np.linalg.norm(W, axis=0)
        assert (norms <= 0.5 + 1e-4).all(), (kwargs, norms.max())
