"""GSPMD ShardingPlan (`parallel/plan.py`) — the unified mesh compiled
into the default fit().

The parity-grid contract (ISSUE 10 acceptance): a plan-sharded fit over
the suite's 8 forced host devices (tests/conftest.py pins
``--xla_force_host_platform_device_count=8`` process-wide, so the flag
cannot leak per-test) must reproduce the single-device fit's loss
trajectory and final params within reduction-order epsilon for

    dp=8,  dp=4 x tp=2 (Megatron rules),  zero_stage in {1, 3}

across the per-call, scan-of-K, and accumulate_steps fit variants —
parallelism is a config choice, never an algorithm change. On top: the
XLA ledger proves ONE compile per (plan, shape) and per-program HBM
argument bytes dropping with zero_stage=3; ResilientTrainer resumes a
checkpoint onto a DIFFERENT zero_stage loudly-but-correctly; the
ParallelWrapper SYNC path is bit-identical to net.fit(plan=...); and
TP servables come out of the same rule table.
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import (
    ShardingPlan, ShardingRules, active_plan, parse_plan, use_mesh,
)
from deeplearning4j_tpu.parallel.plan import leaf_shard_shape


def _mlp(seed=7, lr=5e-2):
    return (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())


def _blob_data(n=256, k=4, d=8, seed=0):
    rs = np.random.RandomState(seed)
    X = np.vstack([rs.randn(n // k, d) * 0.35 + i for i in range(k)]
                  ).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    perm = rs.permutation(n)
    return X[perm], Y[perm]


class _Scores:
    """Per-iteration loss capture (the trajectory the grid compares)."""

    def __init__(self):
        self.vals = []

    def iteration_done(self, net, it, ep, score, etl_ms, bs):
        self.vals.append(score)

    def on_epoch_start(self, net, epoch):
        pass

    def on_epoch_end(self, net, epoch):
        pass


def _fit(plan, epochs=2, seed=7, **kw):
    X, Y = _blob_data()
    net = MultiLayerNetwork(_mlp(seed=seed)).init()
    sc = _Scores()
    net.set_listeners(sc)
    net.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=epochs,
            plan=plan, **kw)
    return net, sc.vals


GRID = [
    ("dp8", ShardingPlan(data=8)),
    ("dp4_tp2", ShardingPlan(data=4, model=2,
                             rules=ShardingRules.megatron())),
    ("zero1", ShardingPlan(data=8, zero_stage=1)),
    ("zero3", ShardingPlan(data=8, zero_stage=3)),
]


@pytest.fixture(scope="module")
def single_device_ref():
    net, traj = _fit(None)
    return np.asarray(net.params_flat()), traj


# ------------------------------------------------------------ parity grid
@pytest.mark.parametrize("name,plan", GRID, ids=[g[0] for g in GRID])
def test_parity_grid_per_call(name, plan, single_device_ref):
    """Plan-sharded fit() == single-device fit() — trajectory AND final
    params — for every point of the dp/tp/zero grid."""
    ref_flat, ref_traj = single_device_ref
    net, traj = _fit(plan)
    np.testing.assert_allclose(traj, ref_traj, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(net.params_flat()), ref_flat,
                               rtol=1e-4, atol=2e-5)


def test_parity_scan_and_accum_paths(single_device_ref):
    """The scan-of-K and gradient-accumulation fit variants run the same
    plan-constrained math (the plan compiles into ALL default-step
    variants, not just per-call)."""
    _, ref_scan = _fit(None, scan_steps=2)
    _, got_scan = _fit(ShardingPlan(data=8), scan_steps=2)
    np.testing.assert_allclose(got_scan, ref_scan, rtol=2e-5, atol=2e-6)
    _, ref_acc = _fit(None, accumulate_steps=2)
    _, got_acc = _fit(ShardingPlan(data=8, zero_stage=1),
                      accumulate_steps=2)
    np.testing.assert_allclose(got_acc, ref_acc, rtol=2e-5, atol=2e-6)


# ----------------------------------------------------- placement contracts
def test_zero3_params_live_sharded_tp_kernels_split():
    net, _ = _fit(ShardingPlan(data=8, zero_stage=3))
    w = net.params["0"]["W"]          # (8, 16): dim 0 divides 8 ways
    assert w.sharding.spec == P("data")
    assert leaf_shard_shape(w) == (1, 16)

    net, _ = _fit(ShardingPlan(data=4, model=2,
                               rules=ShardingRules.megatron()))
    w = net.params["0"]["W"]
    assert w.sharding.spec == P(None, "model")
    assert leaf_shard_shape(w) == (8, 8)


def test_zero1_opt_state_sharded_params_replicated():
    plan = ShardingPlan(data=8, zero_stage=1)
    net, _ = _fit(plan)
    from deeplearning4j_tpu.parallel.zero import zero_spec
    sharded = 0
    for leaf in jax.tree_util.tree_leaves(net.opt_state):
        if zero_spec(leaf, 8) == P("data"):
            assert leaf_shard_shape(leaf)[0] == leaf.shape[0] // 8
            sharded += 1
    assert sharded >= 2               # Adam mu+nu for at least the kernel
    for leaf in jax.tree_util.tree_leaves(net.params):
        assert leaf_shard_shape(leaf) == tuple(leaf.shape)


def test_use_mesh_context_and_plain_fit_transition():
    """Process-wide pickup: an unmodified net.fit() inside use_mesh
    trains sharded; the next plain fit gathers back and runs
    single-device."""
    X, Y = _blob_data()
    net = MultiLayerNetwork(_mlp()).init()
    plan = ShardingPlan(data=8, zero_stage=3)
    with use_mesh(plan):
        assert active_plan() is plan
        net.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    assert active_plan() is None
    assert net.params["0"]["W"].sharding.spec == P("data")
    net.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    assert leaf_shard_shape(net.params["0"]["W"]) == (8, 16)
    # still trains: output usable either way
    assert np.isfinite(net.score())


# ------------------------------------------------- compile-count + memory
def test_one_compile_per_plan_shape_and_zero3_memory_drop():
    """The XLA program ledger proves the perf story: each plan compiles
    its step exactly ONCE per shape (epochs reuse the program), and the
    per-program argument bytes drop by ~data_degree with zero_stage=3
    (params + opt state resident 1/N per device)."""
    from deeplearning4j_tpu.monitor import xla as xla_ledger

    def ledgered_fit(plan):
        xla_ledger.clear_ledger()
        xla_ledger.enable_ledger()
        try:
            _fit(plan, epochs=3)
            recs = [r for r in xla_ledger.records()
                    if r.name == "mln/train_step"]
        finally:
            xla_ledger.disable_ledger()
            xla_ledger.clear_ledger()
        return recs

    dp = ledgered_fit(ShardingPlan(data=8))
    z3 = ledgered_fit(ShardingPlan(data=8, zero_stage=3))
    for recs in (dp, z3):
        assert len(recs) == 1, [r.name for r in recs]
        assert recs[0].compiles == 1          # one compile per (plan, shape)
        assert recs[0].is_sharded
        assert any("'data'" in s for s in recs[0].arg_shardings)
    if dp[0].hbm and z3[0].hbm:               # CPU backend reports both
        dp_args = dp[0].hbm["argument_bytes"]
        z3_args = z3[0].hbm["argument_bytes"]
        # params+opt dominate the arguments; stage 3 shards them 8 ways
        assert z3_args < 0.5 * dp_args, (dp_args, z3_args)


# ------------------------------------------------------- wrapper/inference
def test_wrapper_sync_is_thin_shim_over_plan():
    """ParallelWrapper(SYNC_GRADIENTS) and net.fit(plan=dp) are the SAME
    compiled step — bit-identical trained params."""
    from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
    X, Y = _blob_data()
    ref = MultiLayerNetwork(_mlp(seed=3)).init()
    ref.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=3,
            plan=ShardingPlan(data=8))
    net = MultiLayerNetwork(_mlp(seed=3)).init()
    w = ParallelWrapper(net, mode=TrainingMode.SYNC_GRADIENTS)
    assert w.plan.data_degree == 8            # the wrapper IS a plan now
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=3)
    np.testing.assert_array_equal(np.asarray(net.params_flat()),
                                  np.asarray(ref.params_flat()))


def test_wrapper_adopts_active_plan():
    from deeplearning4j_tpu.parallel import ParallelWrapper
    net = MultiLayerNetwork(_mlp()).init()
    with use_mesh(ShardingPlan(data=4, model=2,
                               rules=ShardingRules.megatron(),
                               zero_stage=1)):
        w = ParallelWrapper(net)
    assert w.plan.model_degree == 2 and w.zero_stage == 1
    assert w.plan.rules is not None


def test_parallel_inference_serves_tp_sharded_servable():
    """Serving loads TP-sharded servables from the SAME rule table
    training used: kernels stay model-sharded in HBM, outputs match the
    single-device forward."""
    from deeplearning4j_tpu.parallel.inference import (
        InferenceMode, ParallelInference,
    )
    X, _ = _blob_data()
    plan = ShardingPlan(data=4, model=2, rules=ShardingRules.megatron())
    net = MultiLayerNetwork(_mlp()).init()
    ref = np.asarray(net.output(X[:64]))
    pi = ParallelInference(net, plan=plan, mode=InferenceMode.SEQUENTIAL)
    got = pi.output(X[:64])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


# --------------------------------------------------------- resume contract
def test_resume_onto_different_zero_stage_is_loud_and_correct(
        tmp_path, caplog, single_device_ref):
    """Preempt under zero_stage=1, resume under zero_stage=3: the
    checkpoint's whole host arrays are re-laundered onto the LIVE plan's
    placements (sharding-aware own_tree), a loud warning names both
    plans, and the trained result matches the uninterrupted run —
    never a silent misplace."""
    import logging
    from deeplearning4j_tpu.train.resilience import ResilientTrainer
    from deeplearning4j_tpu.util.faults import FaultInjector
    ref_flat, _ = single_device_ref
    X, Y = _blob_data()
    ck = str(tmp_path / "ck")
    with use_mesh(ShardingPlan(data=8, zero_stage=1)):
        t1 = ResilientTrainer(MultiLayerNetwork(_mlp()).init(), ck,
                              save_every_n_iterations=2,
                              injector=FaultInjector(preempt_at=5))
        rep1 = t1.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=2)
    assert rep1.preempted and rep1.applied_steps == 5
    with use_mesh(ShardingPlan(data=8, zero_stage=3)), \
            caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
        net = MultiLayerNetwork(_mlp()).init()
        t2 = ResilientTrainer(net, ck, save_every_n_iterations=100)
        rep2 = t2.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=2)
    assert rep2.resumed_from is not None
    assert any("different sharding plan" in r.message for r in caplog.records)
    # restored params live on the LIVE (zero3) placements
    assert net.params["0"]["W"].sharding.spec == P("data")
    np.testing.assert_allclose(np.asarray(net.params_flat()), ref_flat,
                               rtol=1e-4, atol=2e-5)


def test_checkpoint_extra_banks_the_plan(tmp_path):
    from deeplearning4j_tpu.train.resilience import ResilientTrainer
    X, Y = _blob_data()
    ck = str(tmp_path / "ck")
    with use_mesh(ShardingPlan(data=8, zero_stage=1)):
        t = ResilientTrainer(MultiLayerNetwork(_mlp()).init(), ck,
                             save_every_n_iterations=100)
        t.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    entry = t.ckpt.latest_valid()
    extra = t.ckpt.restore_into(MultiLayerNetwork(_mlp()).init(),
                                entry["path"])
    assert extra["plan"] == {"data": 8, "model": 1, "zero_stage": 1,
                             "rules": None}


# ------------------------------------------------------------- plan object
def test_plan_validation_and_parse():
    with pytest.raises(ValueError):
        ShardingPlan(zero_stage=2)
    p = parse_plan("data=4,model=2,rules=megatron,zero=3")
    assert (p.data, p.model, p.zero_stage) == (4, 2, 3)
    assert p.rules is not None
    with pytest.raises(ValueError):
        parse_plan("bogus=1")
    with pytest.raises(ValueError):
        parse_plan("rules=unknown")
    # equal plans compare equal (the fit step-cache key contract)
    assert ShardingPlan(data=8) == ShardingPlan(data=8)
    assert ShardingPlan(data=8) != ShardingPlan(data=8, zero_stage=1)


def test_ragged_batch_falls_back_unsharded():
    """A batch whose dim 0 does not divide the data degree stages
    unsharded (correct, slower) instead of crashing the fit."""
    rs = np.random.RandomState(0)
    X = rs.randn(100, 8).astype("float32")     # 100 % 8 != 0 on the tail
    Y = np.eye(4, dtype="float32")[rs.randint(0, 4, 100)]
    net = MultiLayerNetwork(_mlp()).init()
    net.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1,
            plan=ShardingPlan(data=8))
    assert np.isfinite(net.score())
