"""Keras import tests — numerical parity against live Keras models
(the analog of DL4J's modelimport fixture tests, but generating fixtures
on the fly instead of downloading dl4j-test-resources)."""
import os

import numpy as np
import pytest

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
keras = pytest.importorskip("keras")

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402


def _save(model, tmp_path, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def test_sequential_mlp_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((12,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dropout(0.2),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(0).randn(5, 12).astype("float32")
    expected = np.asarray(m(x))
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_sequential_cnn_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((16, 16, 3)),
        keras.layers.Conv2D(8, 3, activation="relu", padding="same"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Conv2D(4, 3, padding="valid", activation="tanh"),
        keras.layers.Flatten(),
        keras.layers.Dense(5, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(1).rand(3, 16, 16, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_sequential_batchnorm_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((8, 8, 2)),
        keras.layers.Conv2D(4, 3, padding="same"),
        keras.layers.BatchNormalization(),
        keras.layers.Activation("relu"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(2, activation="softmax"),
    ])
    # make BN stats non-trivial
    m.compile(optimizer="sgd", loss="categorical_crossentropy")
    rs = np.random.RandomState(2)
    m.fit(rs.rand(32, 8, 8, 2), np.eye(2)[rs.randint(0, 2, 32)],
          epochs=1, verbose=0)
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = rs.rand(4, 8, 8, 2).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_sequential_lstm_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.LSTM(5, return_sequences=True),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(3).rand(2, 6, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_functional_residual_parity(tmp_path):
    inp = keras.layers.Input((10,), name="inp")
    h = keras.layers.Dense(10, activation="tanh", name="h1")(inp)
    s = keras.layers.Add(name="res")([h, inp])
    out = keras.layers.Dense(4, activation="softmax", name="out")(s)
    m = keras.Model(inp, out)
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_model_and_weights(p)
    x = np.random.RandomState(4).randn(3, 10).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)


def test_imported_model_can_finetune(tmp_path):
    # Compiled model: import honors the saved optimizer (training_config),
    # the analog of DL4J's enforceTrainingConfig optimizer import.
    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(2, activation="softmax"),
    ])
    m.compile(optimizer=keras.optimizers.Adam(0.02),
              loss="categorical_crossentropy")
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    assert abs(net.conf.updater.learning_rate - 0.02) < 1e-9
    rs = np.random.RandomState(5)
    X = rs.randn(64, 6).astype("float32")
    Y = np.eye(2, dtype="float32")[(X[:, 0] > 0).astype(int)]
    net.fit((X, Y), epochs=40, batch_size=16)
    assert net.evaluate((X, Y)).accuracy() > 0.8


def test_imported_model_transfer_learning_finetune(tmp_path):
    # Uncompiled model: fine-tune via the TransferLearning surgery path
    # with an explicit updater (DL4J TransferLearning.Builder +
    # FineTuneConfiguration workflow on an imported net).
    from deeplearning4j_tpu.nn.transferlearning import (
        FineTuneConfiguration, TransferLearning,
    )
    from deeplearning4j_tpu.nn.updaters import Adam
    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(2, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    tuned = (TransferLearning(net)
             .fine_tune_configuration(FineTuneConfiguration(updater=Adam(0.02)))
             .build())
    rs = np.random.RandomState(5)
    X = rs.randn(64, 6).astype("float32")
    Y = np.eye(2, dtype="float32")[(X[:, 0] > 0).astype(int)]
    tuned.fit((X, Y), epochs=40, batch_size=16)
    assert tuned.evaluate((X, Y)).accuracy() > 0.8


def test_unsupported_layer_raises(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((4, 4, 1)),
        keras.layers.ConvLSTM1D(2, 3),    # no mapper for ConvLSTM family
    ])
    p = _save(m, tmp_path)
    with pytest.raises(ValueError, match="Unsupported Keras layer"):
        KerasModelImport.import_keras_model_and_weights(p)


# -------------------------------------------- round-3 mapper breadth parity
def test_conv2d_transpose_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((7, 7, 3)),
        keras.layers.Conv2DTranspose(5, 3, strides=2, padding="same",
                                     activation="relu"),
        keras.layers.Conv2DTranspose(2, 3, strides=1, padding="valid"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(3).rand(2, 7, 7, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_separable_and_depthwise_conv_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((10, 10, 4)),
        keras.layers.SeparableConv2D(6, 3, padding="same",
                                     depth_multiplier=2,
                                     activation="relu"),
        keras.layers.DepthwiseConv2D(3, padding="valid",
                                     depth_multiplier=1),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(4).rand(2, 10, 10, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_gru_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.GRU(5, return_sequences=True),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(5).randn(2, 6, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_gru_reset_after_false_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((5, 3)),
        keras.layers.GRU(4, return_sequences=True, reset_after=False),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(6).randn(2, 5, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_time_distributed_dense_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.LSTM(5, return_sequences=True),
        keras.layers.TimeDistributed(keras.layers.Dense(3,
                                                        activation="tanh")),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(7).randn(2, 6, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_cropping_and_zeropadding_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((9, 9, 2)),
        keras.layers.ZeroPadding2D(((1, 2), (0, 3))),
        keras.layers.Cropping2D(((2, 1), (1, 0))),
        keras.layers.Conv2D(3, 3),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(8).rand(2, 9, 9, 2).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_text_cnn_1d_parity(tmp_path):
    """Conv1D / MaxPooling1D / GlobalAveragePooling1D — the Keras text-CNN
    family."""
    m = keras.Sequential([
        keras.layers.Input((20, 8)),
        keras.layers.Conv1D(12, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling1D(2),
        keras.layers.Conv1D(6, 3, padding="valid"),
        keras.layers.GlobalAveragePooling1D(),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(9).randn(4, 20, 8).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_upsampling_and_advanced_activations_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 6, 2)),
        keras.layers.Conv2D(4, 3, padding="same"),
        keras.layers.LeakyReLU(negative_slope=0.2),
        keras.layers.UpSampling2D(2),
        keras.layers.Conv2D(2, 3, padding="same"),
        keras.layers.ELU(alpha=0.7),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(10).rand(2, 6, 6, 2).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


# ------------------------- round-4 mapper surface (VERDICT item 2) ----------

def test_simple_rnn_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.SimpleRNN(5, return_sequences=True),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(10).randn(2, 6, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_lstm_return_sequences_false_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.LSTM(5),               # return_sequences=False
        keras.layers.Dense(3, activation="tanh"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(11).randn(2, 6, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_bidirectional_lstm_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.Bidirectional(keras.layers.LSTM(
            5, return_sequences=True)),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(12).randn(2, 6, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_bidirectional_last_step_and_sum_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((5, 3)),
        keras.layers.Bidirectional(keras.layers.GRU(4), merge_mode="sum"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(13).randn(2, 5, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_masking_lstm_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.Masking(mask_value=0.0),
        keras.layers.LSTM(5),               # last valid step's output
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(14).randn(2, 6, 4).astype("float32")
    x[:, 4:, :] = 0.0                       # trailing masked steps
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_permute_and_repeat_vector_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(6, activation="relu"),
        keras.layers.RepeatVector(4),
        keras.layers.Permute((2, 1)),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(15).randn(3, 8).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)


def test_noise_layers_identity_at_inference_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((10,)),
        keras.layers.GaussianNoise(0.3),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.GaussianDropout(0.2),
        keras.layers.Dense(6, activation="relu"),
        keras.layers.AlphaDropout(0.1),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(16).randn(4, 10).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)
    # train mode actually perturbs
    acts = net.feed_forward(x, train=True)
    assert not np.allclose(np.asarray(acts[-1]), np.asarray(m(x)),
                           atol=1e-6)


def test_spatial_dropout_conv_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.SpatialDropout2D(0.5),
        keras.layers.Conv2D(4, 3, activation="relu"),
        keras.layers.SpatialDropout2D(0.3),
        keras.layers.Flatten(),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(17).randn(2, 8, 8, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)


def test_cropping_padding_upsampling_1d_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((12, 3)),
        keras.layers.Cropping1D((2, 1)),
        keras.layers.UpSampling1D(2),
        keras.layers.ZeroPadding1D((1, 2)),
        keras.layers.Conv1D(4, 3, activation="relu"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(18).randn(2, 12, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_locally_connected_config_import():
    """Keras 3 removed LocallyConnected*; the mapper covers Keras-2-era
    archives. Verify the config mapping + untied-weights math directly."""
    from deeplearning4j_tpu.modelimport.keras import _map_layer
    layer, loader = _map_layer(
        "LocallyConnected1D",
        {"filters": 4, "kernel_size": [3], "strides": [1],
         "padding": "valid", "activation": "linear", "use_bias": True},
        False, sequence=True)
    from deeplearning4j_tpu.nn.conf.base import InputType
    import jax
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.recurrent(2, 6))
    assert params["W"].shape == (4, 3 * 2, 4)   # (ot, k*c, f)
    rs = np.random.RandomState(19)
    W = rs.randn(4, 6, 4).astype("float32")
    b = rs.randn(4, 4).astype("float32")
    loader(params, state, [W, b])
    x = rs.randn(2, 6, 2).astype("float32")
    y, _ = layer.apply(params, state, x)
    # manual untied conv
    want = np.zeros((2, 4, 4), np.float32)
    for o in range(4):
        patch = x[:, o:o + 3, :].reshape(2, -1)
        want[:, o, :] = patch @ W[o] + b[o]
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5)


def test_locally_connected_2d_math():
    from deeplearning4j_tpu.modelimport.keras import _map_layer
    from deeplearning4j_tpu.nn.conf.base import InputType
    import jax
    layer, loader = _map_layer(
        "LocallyConnected2D",
        {"filters": 3, "kernel_size": [2, 2], "strides": [1, 1],
         "padding": "valid", "activation": "linear", "use_bias": True},
        False)
    params, state = layer.init(jax.random.PRNGKey(1),
                               InputType.convolutional(4, 5, 2))
    oh, ow = 3, 4
    assert params["W"].shape == (oh * ow, 2 * 2 * 2, 3)
    rs = np.random.RandomState(20)
    W = rs.randn(oh * ow, 8, 3).astype("float32")
    b = rs.randn(oh, ow, 3).astype("float32")
    loader(params, state, [W, b])
    x = rs.randn(2, 4, 5, 2).astype("float32")
    y, _ = layer.apply(params, state, x)
    want = np.zeros((2, oh, ow, 3), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i:i + 2, j:j + 2, :].reshape(2, -1)
            want[:, i, j, :] = patch @ W[i * ow + j] + b[i, j]
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5)


def test_mixed_masked_bidirectional_chain_parity(tmp_path):
    """Mask must propagate through the WHOLE chain (Keras semantics), and
    the backward direction's flipped (valid-suffix) mask must resolve to
    the right last step — regression for both round-4 masking bugs."""
    m = keras.Sequential([
        keras.layers.Input((10, 6)),
        keras.layers.Masking(mask_value=0.0),
        keras.layers.Bidirectional(keras.layers.LSTM(
            8, return_sequences=True)),
        keras.layers.SpatialDropout1D(0.2),
        keras.layers.Bidirectional(keras.layers.GRU(6)),
        keras.layers.GaussianNoise(0.1),
        keras.layers.Dense(4, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(21).randn(3, 10, 6).astype("float32")
    x[:, 7:, :] = 0.0
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_functional_masked_rnn_chain_parity(tmp_path):
    """Functional-model masking must propagate through stacked RNNs."""
    inp = keras.layers.Input((8, 5))
    h = keras.layers.Masking(0.0)(inp)
    h = keras.layers.LSTM(6, return_sequences=True)(h)
    h = keras.layers.LSTM(4)(h)
    out = keras.layers.Dense(3, activation="softmax")(h)
    m = keras.Model(inp, out)
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_model_and_weights(p)
    x = np.random.RandomState(22).randn(2, 8, 5).astype("float32")
    x[:, 5:, :] = 0.0
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-4)


def test_masking_through_dense_raises_clear_error(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.Masking(0.0),
        keras.layers.LSTM(5, return_sequences=True),
        keras.layers.Dense(4, activation="relu"),
        keras.layers.LSTM(3),
    ])
    p = _save(m, tmp_path)
    with pytest.raises(ValueError, match="cannot propagate"):
        KerasModelImport.import_keras_sequential_model_and_weights(p)


def test_reshape_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((24,)),
        keras.layers.Dense(18, activation="relu"),
        keras.layers.Reshape((6, 3)),
        keras.layers.Conv1D(4, 3, activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(2, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(23).randn(3, 24).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)


def test_reshape_to_image_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((48,)),
        keras.layers.Reshape((4, 4, 3)),
        keras.layers.Conv2D(5, 2, activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(3, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(24).randn(2, 48).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)


def test_lrn_config_mapping():
    """Keras 3 has no LRN layer; the mapper covers Keras-2-era custom
    archives (KerasLRN.java). Verify config mapping + math directly."""
    from deeplearning4j_tpu.modelimport.keras import _map_layer
    layer, loader = _map_layer(
        "LRN", {"k": 1.0, "n": 3, "alpha": 0.01, "beta": 0.5}, False)
    assert loader is None
    from deeplearning4j_tpu.nn.conf.base import InputType
    import jax
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.convolutional(4, 4, 6))
    import jax.numpy as jnp
    x = np.random.RandomState(25).randn(2, 4, 4, 6).astype("float32")
    y, _ = layer.apply(params, state, jnp.asarray(x))
    assert np.asarray(y).shape == x.shape
    assert np.isfinite(np.asarray(y)).all()


def test_reshape_with_inferred_dim_parity(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((24,)),
        keras.layers.Reshape((-1, 3)),          # inferred T=8
        keras.layers.Conv1D(4, 3, activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(2, activation="softmax"),
    ])
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    x = np.random.RandomState(26).randn(3, 24).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)


def test_loss_and_atrous_config_mapping():
    """KerasLoss.java + KerasAtrousConvolution mappers (Keras-1/2-era
    archives; Keras 3 has neither, so map configs directly)."""
    from deeplearning4j_tpu.modelimport.keras import _map_layer
    from deeplearning4j_tpu.nn.layers import (
        Convolution1DLayer, ConvolutionLayer, LossLayer, RnnLossLayer,
    )
    layer, loader = _map_layer("Loss", {"loss": "binary_crossentropy"},
                               True)
    assert isinstance(layer, LossLayer) and layer.loss == "xent"
    layer, _ = _map_layer("Loss", {"loss": "categorical_crossentropy"},
                          True, sequence=True)
    assert isinstance(layer, RnnLossLayer) and layer.loss == "mcxent"
    layer, _ = _map_layer(
        "AtrousConvolution1D",
        {"filters": 4, "kernel_size": [3], "atrous_rate": [2],
         "padding": "same", "activation": "relu"}, False, sequence=True)
    assert isinstance(layer, Convolution1DLayer) and layer.dilation == 2
    layer, _ = _map_layer(
        "AtrousConvolution2D",
        {"filters": 4, "kernel_size": [3, 3], "atrous_rate": [2, 2],
         "padding": "same", "activation": "relu"}, False)
    assert isinstance(layer, ConvolutionLayer) and layer.dilation == (2, 2)


def test_compiled_loss_flows_to_output_layer(tmp_path):
    """The training_config loss (KerasLoss role) must override the
    activation heuristic on the imported output layer."""
    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(1, activation="sigmoid"),
    ])
    m.compile(optimizer="adam", loss="binary_crossentropy")
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(p)
    assert type(net.layers[-1]).__name__ == "OutputLayer"
    assert net.layers[-1].loss == "xent"
    x = np.random.RandomState(27).randn(4, 6).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(m(x)), atol=1e-5)
    # and it trains against that loss
    y = (np.random.RandomState(28).rand(32, 1) > 0.5).astype("float32")
    X = np.random.RandomState(29).randn(32, 6).astype("float32")
    net.fit((X, y), batch_size=16, epochs=2)
    assert np.isfinite(net.score())


def test_functional_shared_layer_parity(tmp_path):
    """A layer called at two sites (Keras weight sharing) imports as
    per-call-site vertices with copied weights — forward parity exact
    (previously silently wrong: both calls' inputs were concatenated
    into one vertex)."""
    shared = keras.layers.Dense(4, activation="relu", name="shared")
    ia = keras.layers.Input((3,), name="a")
    ib = keras.layers.Input((3,), name="b")
    merged = keras.layers.Concatenate()([shared(ia), shared(ib)])
    out = keras.layers.Dense(2, activation="softmax")(merged)
    m = keras.Model([ia, ib], out)
    p = _save(m, tmp_path)
    net = KerasModelImport.import_keras_model_and_weights(p)
    xa = np.random.RandomState(30).randn(4, 3).astype("float32")
    xb = np.random.RandomState(31).randn(4, 3).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(xa, xb)),
                               np.asarray(m([xa, xb])), atol=1e-5)
    # both call-site vertices hold the same (copied) weights
    np.testing.assert_array_equal(np.asarray(net.params["shared"]["W"]),
                                  np.asarray(net.params["shared__call1"]["W"]))
