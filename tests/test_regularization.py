"""Dropout variants, weight noise, and constraints — behavioral tests
(the analog of DL4J's TestDropout / TestWeightNoise / TestConstraints)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.regularization import (
    AlphaDropout, DropConnect, Dropout, GaussianDropout, GaussianNoise,
    MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint,
    UnitNormConstraint, WeightNoise,
)
from deeplearning4j_tpu.nn.updaters import Sgd

RS = np.random.RandomState(0)


def _blobs(n=96, f=6, c=3):
    X = RS.randn(n, f).astype("float32")
    Y = np.eye(c, dtype="float32")[RS.randint(0, c, n)]
    return X, Y


def _fit_net(layer0, layer1=None, epochs=4):
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.1)).list()
            .layer(layer0)
            .layer(layer1 or OutputLayer(n_out=3, activation="softmax",
                                         loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    X, Y = _blobs()
    net.fit((X, Y), epochs=epochs, batch_size=32)
    assert np.isfinite(net.score())
    return net


# ------------------------------------------------------------ dropout family
def test_alpha_dropout_preserves_selu_statistics():
    # AlphaDropout on standard-normal input (SELU fixed point): mean/var
    # preserved to statistical tolerance (AlphaDropout.java contract)
    x = jnp.asarray(RS.randn(200_000).astype("float32"))
    y = AlphaDropout(p=0.1).apply(x, jax.random.PRNGKey(0))
    assert abs(float(y.mean())) < 0.02
    assert abs(float(y.var()) - 1.0) < 0.05
    # dropped units take the alpha' value, not zero
    assert float((y == 0).mean()) < 1e-3


def test_gaussian_dropout_preserves_mean():
    x = jnp.ones((100_000,), "float32") * 3.0
    y = GaussianDropout(rate=0.25).apply(x, jax.random.PRNGKey(1))
    assert abs(float(y.mean()) - 3.0) < 0.02
    expected_std = 3.0 * (0.25 / 0.75) ** 0.5
    assert abs(float(y.std()) - expected_std) < 0.05


def test_gaussian_noise_additive():
    x = jnp.zeros((100_000,), "float32")
    y = GaussianNoise(stddev=0.5).apply(x, jax.random.PRNGKey(2))
    assert abs(float(y.std()) - 0.5) < 0.02
    assert abs(float(y.mean())) < 0.02


def test_dropout_object_matches_float_semantics():
    x = jnp.ones((100_000,), "float32")
    y = Dropout(p=0.3).apply(x, jax.random.PRNGKey(3))
    drop_frac = float((y == 0).mean())
    assert abs(drop_frac - 0.3) < 0.02
    assert abs(float(y.mean()) - 1.0) < 0.02       # inverted scaling


def test_dropout_variants_train_only_and_nets_train():
    for do in (AlphaDropout(p=0.1), GaussianDropout(rate=0.1),
               GaussianNoise(stddev=0.1), Dropout(p=0.2)):
        net = _fit_net(DenseLayer(n_out=10, activation="selu", dropout=do))
        X, _ = _blobs()
        # eval-mode forward is deterministic (no dropout applied)
        a = np.asarray(net.output(X[:8]))
        b = np.asarray(net.output(X[:8]))
        np.testing.assert_allclose(a, b)


# -------------------------------------------------------- weight noise family
def test_dropconnect_transform_and_training():
    w = jnp.ones((50, 50), "float32")
    out = DropConnect(p=0.4).transform({"W": w}, jax.random.PRNGKey(0))
    dropped = float((out["W"] == 0).mean())
    assert abs(dropped - 0.4) < 0.03
    kept = np.asarray(out["W"])[np.asarray(out["W"]) != 0]
    np.testing.assert_allclose(kept, 1.0 / 0.6, rtol=1e-5)
    # biases untouched by default
    out2 = DropConnect(p=0.9).transform({"W": w, "b": jnp.ones(5)},
                                        jax.random.PRNGKey(1))
    np.testing.assert_allclose(np.asarray(out2["b"]), 1.0)
    net = _fit_net(DenseLayer(n_out=10, activation="relu",
                              weight_noise=DropConnect(p=0.3)))
    # weight noise is train-only: eval forward deterministic
    X, _ = _blobs()
    np.testing.assert_allclose(np.asarray(net.output(X[:4])),
                               np.asarray(net.output(X[:4])))


def test_weight_noise_additive_and_multiplicative():
    w = jnp.full((80, 80), 2.0, "float32")
    add = WeightNoise(stddev=0.1, additive=True).transform(
        {"W": w}, jax.random.PRNGKey(0))["W"]
    assert abs(float(add.mean()) - 2.0) < 0.01
    assert abs(float(add.std()) - 0.1) < 0.01
    mul = WeightNoise(stddev=0.1, additive=False).transform(
        {"W": w}, jax.random.PRNGKey(1))["W"]
    assert abs(float(mul.std()) - 0.2) < 0.02      # 2.0 * 0.1
    _fit_net(DenseLayer(n_out=10, activation="relu",
                        weight_noise=WeightNoise(stddev=0.05)))


# ---------------------------------------------------------- constraint family
def _col_norms(W):
    return np.linalg.norm(np.asarray(W), axis=0)


def test_max_norm_constraint_enforced_after_updates():
    net = _fit_net(DenseLayer(n_out=10, activation="tanh",
                              constraints=(MaxNormConstraint(max_norm=0.5),)))
    assert (_col_norms(net.params["0"]["W"]) <= 0.5 + 1e-5).all()


def test_unit_norm_constraint():
    net = _fit_net(DenseLayer(n_out=10, activation="tanh",
                              constraints=(UnitNormConstraint(),)))
    np.testing.assert_allclose(_col_norms(net.params["0"]["W"]), 1.0,
                               atol=1e-5)


def test_min_max_norm_constraint():
    net = _fit_net(DenseLayer(
        n_out=10, activation="tanh",
        constraints=(MinMaxNormConstraint(min_norm=0.4, max_norm=0.8),)))
    norms = _col_norms(net.params["0"]["W"])
    assert (norms >= 0.4 - 1e-5).all() and (norms <= 0.8 + 1e-5).all()


def test_non_negative_constraint():
    net = _fit_net(DenseLayer(n_out=10, activation="sigmoid",
                              constraints=(NonNegativeConstraint(),)))
    assert (np.asarray(net.params["0"]["W"]) >= 0).all()
    # bias unconstrained by default (apply_to_bias=False)


def test_constraint_on_output_layer_too():
    net = _fit_net(
        DenseLayer(n_out=8, activation="tanh"),
        OutputLayer(n_out=3, activation="softmax", loss="mcxent",
                    constraints=(MaxNormConstraint(max_norm=1.0),)))
    assert (_col_norms(net.params["1"]["W"]) <= 1.0 + 1e-5).all()


# -------------------------------------------------------------------- serde
def test_regularization_serde_round_trip():
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    conf = (NeuralNetConfiguration.Builder().seed(5).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="selu",
                              dropout=AlphaDropout(p=0.07),
                              weight_noise=DropConnect(p=0.25),
                              constraints=(MaxNormConstraint(max_norm=1.5),
                                           NonNegativeConstraint())))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent",
                               dropout=GaussianNoise(stddev=0.2)))
            .set_input_type(InputType.feed_forward(6)).build())
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back.layers[0].dropout == AlphaDropout(p=0.07)
    assert back.layers[0].weight_noise == DropConnect(p=0.25)
    assert back.layers[0].constraints == (MaxNormConstraint(max_norm=1.5),
                                          NonNegativeConstraint())
    assert back.layers[1].dropout == GaussianNoise(stddev=0.2)
    # and the deserialized conf actually trains
    net = MultiLayerNetwork(back).init()
    X, Y = _blobs()
    net.fit((X, Y), epochs=2, batch_size=32)
    assert np.isfinite(net.score())
