"""Continuous-batching decode subsystem tests (serving/decode.py,
serving/kvcache.py, serving/quantize.py + the HTTP/router surfaces).

The load-bearing one is test_late_join_streams_before_batch_drains: the
continuous-batching acceptance criterion is proven by the SCHEDULER (a
late request's first token lands while an earlier generation is still
streaming), not inferred from throughput.
"""
import json
import os
import queue
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.models.transformer import TransformerLM
from deeplearning4j_tpu.serving import (
    ModelRegistry, ModelServer, ServerOverloadedError,
)
from deeplearning4j_tpu.serving.decode import (
    DecodeConfig, DecodeEngine, DecodeScheduler, GenerateRequest, ServedLM,
)
from deeplearning4j_tpu.serving.kvcache import DUMP_PAGE, KVCacheState
from deeplearning4j_tpu.serving.quantize import (
    QTensor, quality_delta, quantize_leaf,
)
from deeplearning4j_tpu.serving.registry import (
    ModelLoadError, load_servable, parse_zoo_source,
)

ZOO_SRC = ("zoo:TransformerLM?vocab_size=48&n_layers=1&n_embd=32"
           "&n_heads=4&seq_length=32")


def drain_events(req, timeout=30.0):
    """Collect ((kind, payload, t_monotonic)) until done/error."""
    out = []
    deadline = time.monotonic() + timeout
    while True:
        ev = req.events.get(timeout=max(0.1, deadline - time.monotonic()))
        out.append((ev[0], ev[1], time.monotonic()))
        if ev[0] in ("done", "error"):
            return out


# ------------------------------------------------------------- kv cache
def test_kvcache_alloc_release_and_dump_page():
    c = KVCacheState(slots=2, page_size=4, max_context=16, name="kvt")
    assert c.pool_pages == 1 + 2 * 4          # page 0 is the dump page
    s = c.admit(6)                            # needs ceil(6/4) = 2 pages
    assert s is not None
    assert c.describe()["pages_used"] == 2
    assert (c.page_table[s, :2] > 0).all()    # never the dump page
    assert (c.page_table[s, 2:] == 0).all()
    # position 6 lives inside page 1 (already allocated); 8 needs page 2
    assert c.ensure_page(s)
    c.seq_lens[s] = 8
    assert c.ensure_page(s)
    assert c.describe()["pages_used"] == 3
    c.release(s)
    assert c.describe()["pages_used"] == 0
    assert not c.active[s]


def test_kvcache_exhaustion_blocks_admission_and_growth():
    # pool sized for exactly one max-context sequence
    c = KVCacheState(slots=2, page_size=4, max_context=16, pool_pages=5,
                     name="kvx")
    a = c.admit(16 - 4)
    assert a is not None                      # took 3 of 4 pages
    assert c.admit(8) is None                 # 2 pages wanted, 1 free
    b = c.admit(3)                            # 1 page still fits
    assert b is not None
    c.seq_lens[b] = 4
    assert not c.ensure_page(b)               # pool dry -> stall, no crash
    c.release(a)
    assert c.ensure_page(b)                   # freed pages recycle


def test_kvcache_rejects_unaligned_context():
    with pytest.raises(ValueError):
        KVCacheState(slots=1, page_size=8, max_context=20)


# ------------------------------------------------- kv prefix cache (CoW)
def test_kvcache_prefix_reuse_refcount_lifecycle():
    """Full-block prefix sharing: a second prompt with a common prefix
    maps the SAME physical pages (ref 2), release retains indexed pages
    instead of freeing, and a later identical prefix still hits."""
    c = KVCacheState(slots=4, page_size=4, max_context=16, name="kvp")
    t = np.arange(12, dtype=np.int32)             # 3 full blocks
    a = c.admit_prompt(t)
    assert a.cached_len == 0 and a.cow_src is None  # cold
    c.register_prefix(a.slot, t)
    # shares the first 2 blocks, diverges in the third
    b = c.admit_prompt(np.concatenate([t[:8], [99, 98]]).astype(np.int32))
    assert b.cached_len == 8
    assert (c.page_table[b.slot, :2] == c.page_table[a.slot, :2]).all()
    shared_page = int(c.page_table[a.slot, 0])
    assert c.ref_count(shared_page) == 2
    c.release(a.slot)
    assert c.ref_count(shared_page) == 1          # b still maps it
    c.release(b.slot)
    assert c.ref_count(shared_page) == 0
    # indexed pages went to the retained set, not the free list (b's
    # partial third page was never indexed and freed immediately): the
    # prefix is still hot for the next admission
    assert c.retained_pages() == 3                # a's 3 indexed blocks
    assert c.cached_prefix_len(t) == 12
    d = c.admit_prompt(np.concatenate([t, [7]]).astype(np.int32))
    assert d.cached_len == 12                     # full retained chain hit
    c.release(d.slot)
    hits = monitor.counter("serving_decode_kv_cache_hits_total", "x",
                           labels=("model",)).value(model="kvp")
    misses = monitor.counter("serving_decode_kv_cache_misses_total", "x",
                             labels=("model",)).value(model="kvp")
    assert hits == 2 and misses == 1


def test_kvcache_cow_on_full_prefix_and_dump_page_never_shared():
    """A page-aligned prompt whose every block is cached still must
    recompute its last token — admit hands back a copy-on-write pair so
    the recompute writes a private copy, never the shared page. The dump
    page is never indexed, shared, or a COW endpoint."""
    c = KVCacheState(slots=4, page_size=4, max_context=16, name="kvcow")
    t = np.arange(8, dtype=np.int32)              # exactly 2 blocks
    a = c.admit_prompt(t)
    c.register_prefix(a.slot, t)
    b = c.admit_prompt(t)                         # identical, fully cached
    assert b.cached_len == 7                      # forced last-token redo
    assert b.cow_src == int(c.page_table[a.slot, 1])
    assert b.cow_dst == int(c.page_table[b.slot, 1])
    assert b.cow_dst not in (b.cow_src, DUMP_PAGE)
    # block 1 shared read-only; block 2 diverged onto the private copy
    assert c.page_table[b.slot, 0] == c.page_table[a.slot, 0]
    assert c.page_table[b.slot, 1] != c.page_table[a.slot, 1]
    # the source is pinned until the engine's on-device copy completes
    assert c.ref_count(b.cow_src) == 2            # a's mapping + the pin
    c.unref_page(b.cow_src)
    assert c.ref_count(b.cow_src) == 1
    c.release(a.slot)
    c.release(b.slot)
    assert c.ref_count(DUMP_PAGE) == 0
    assert DUMP_PAGE not in c._by_page            # never indexed
    # no live table maps the dump page as an allocated entry
    assert all(c._pages_per_slot_live[s] == 0 for s in range(c.slots))


def test_kvcache_lru_eviction_under_pool_pressure():
    """Retained prefixes are cache, not working memory: when the free
    list runs dry, the LRU chain is evicted (and unindexed) to satisfy
    new admissions; fresher chains survive."""
    c = KVCacheState(slots=2, page_size=4, max_context=8, pool_pages=5,
                     name="kvev")                 # 4 usable pages
    a_t = np.arange(8, dtype=np.int32)
    b_t = np.arange(8, dtype=np.int32) + 100
    c_t = np.arange(8, dtype=np.int32) + 200
    a = c.admit_prompt(a_t)
    c.register_prefix(a.slot, a_t)
    c.release(a.slot)
    b = c.admit_prompt(b_t)
    c.register_prefix(b.slot, b_t)
    c.release(b.slot)
    assert c.retained_pages() == 4 and c.free_pages() == 4
    ev0 = monitor.counter("serving_decode_kv_cache_evictions_total", "x",
                          labels=("model",)).value(model="kvev")
    d = c.admit_prompt(c_t)                       # needs 2 fresh pages
    assert d is not None and d.cached_len == 0
    ev1 = monitor.counter("serving_decode_kv_cache_evictions_total", "x",
                          labels=("model",)).value(model="kvev")
    assert ev1 - ev0 == 2                         # a's chain went, LRU
    assert c.cached_prefix_len(a_t) == 0          # evicted
    assert c.cached_prefix_len(b_t) == 8          # fresher chain survived
    c.release(d.slot)


def test_kvcache_tokenless_admit_keeps_legacy_semantics():
    """admit(int) (no tokens) must neither share nor retain: release
    frees everything immediately, exactly the pre-cache behavior."""
    c = KVCacheState(slots=2, page_size=4, max_context=16, name="kvleg")
    s = c.admit(10)
    assert s is not None
    c.release(s)
    assert c.retained_pages() == 0
    assert c.free_pages() == c.pool_pages - 1


# ------------------------------------------------------------ zoo kwargs
def test_zoo_source_constructor_kwargs():
    arch, kwargs = parse_zoo_source(
        "TransformerLM?n_layers=2&vocab_size=512&dropout=0.1"
        "&use_rope=false")
    assert arch == "TransformerLM"
    assert kwargs == {"n_layers": 2, "vocab_size": 512, "dropout": 0.1,
                      "use_rope": False}
    net = load_servable(ZOO_SRC)
    # layer 0 embedding table reflects the requested sizing
    assert net.params["0"]["W"].shape == (48, 32)
    # tuple coercion for shape-valued fields
    lenet = load_servable("zoo:LeNet?num_classes=5&input_shape=28,28,1")
    assert lenet.layers[-1].n_out == 5


def test_zoo_source_bad_kwarg_is_clean_error():
    with pytest.raises(ModelLoadError):
        load_servable("zoo:TransformerLM?definitely_not_a_field=3")
    with pytest.raises(ModelLoadError):
        load_servable("zoo:NoSuchArch?x=1")


# ------------------------------------------------------------- quantize
def test_quantize_leaf_roundtrip_and_pytree():
    rs = np.random.RandomState(0)
    w = rs.randn(32, 16).astype(np.float32)
    q = quantize_leaf(w)
    assert isinstance(q, QTensor) and q.q.dtype == np.int8
    deq = np.asarray(q.dequant())
    # per-channel symmetric int8: worst-case error is half a step
    step = np.abs(w).max(axis=0) / 127.0
    assert (np.abs(deq - w) <= step[None, :] * 0.5 + 1e-7).all()
    # QTensor flows through jax pytrees (jit params)
    import jax
    leaves, treedef = jax.tree_util.tree_flatten({"w": q})
    assert len(leaves) == 2
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(back["w"], QTensor)


@pytest.fixture(scope="module")
def quant_engines():
    net = TransformerLM(vocab_size=48, seq_length=32, n_layers=1,
                        n_embd=32, n_heads=4, seed=21).init()
    cfg = DecodeConfig(slots=2, page_size=8)
    base = DecodeEngine(net, cfg, name="q-base")
    i8 = DecodeEngine(net, DecodeConfig(slots=2, page_size=8,
                                        quantize="int8"), name="q-int8")
    b16 = DecodeEngine(net, DecodeConfig(slots=2, page_size=8,
                                         quantize="bf16"), name="q-bf16")
    return base, i8, b16


def test_quantized_variants_measured_quality(quant_engines):
    base, i8, b16 = quant_engines
    rs = np.random.RandomState(3)
    toks = rs.randint(0, 48, (4, 24))
    for eng in (i8, b16):
        d = quality_delta(base, eng, toks)
        assert np.isfinite(d["ppl_variant"]) and np.isfinite(d["logit_mae"])
        # weight-only PTQ of a small model: quality moves by percents,
        # not orders of magnitude
        assert abs(d["ppl_delta_pct"]) < 25.0, d
    # int8 really stores int8
    p = i8._params
    assert isinstance(p["1"]["attn"]["Wq"], QTensor)
    assert isinstance(p["0"]["W"], QTensor)


def test_quantized_engine_generates(quant_engines):
    _, i8, b16 = quant_engines
    for eng in (i8, b16):
        eng.warm()
        slot = eng.cache.admit(3)
        tok, _ = eng.prefill(slot, np.array([1, 2, 3], np.int32), 0.0, 0)
        assert 0 <= tok < 48
        toks, act, _ = eng.step()
        assert act[slot] and 0 <= int(toks[slot]) < 48
        eng.cache.release(slot)


# --------------------------------------------------- continuous batching
@pytest.fixture(scope="module")
def served_lm():
    lm = ServedLM("cb-lm", load_servable(ZOO_SRC), ZOO_SRC,
                  decode=DecodeConfig(slots=2, page_size=8,
                                      queue_limit=8))
    yield lm
    lm.shutdown(drain=False, timeout=5)


def test_late_join_streams_before_batch_drains(served_lm):
    """THE continuous-batching proof: request B, submitted while A is
    mid-generation, gets its first token before A finishes — token-level
    join, not request-level batching."""
    joins_before = monitor.counter(
        "serving_decode_preempted_joins_total", "x",
        labels=("model",)).value(model="cb-lm")
    a = served_lm.generate([1, 2, 3], max_new_tokens=24,
                           temperature=0.7, top_k=8)
    # wait until A is genuinely mid-stream
    first_a = a.events.get(timeout=30)
    assert first_a[0] == "token"
    b = served_lm.generate([4, 5], max_new_tokens=4)
    b_events = drain_events(b)
    a_events = drain_events(a)
    assert b_events[-1][0] == "done" and a_events[-1][0] == "done"
    b_first_token_t = b_events[0][2]
    a_done_t = a_events[-1][2]
    assert b_first_token_t < a_done_t, \
        "late join waited for the running batch to drain"
    # and the scheduler metered the mid-flight join
    joins_after = monitor.counter(
        "serving_decode_preempted_joins_total", "x",
        labels=("model",)).value(model="cb-lm")
    assert joins_after > joins_before


def test_eos_and_temperature_sampling(served_lm):
    # greedy run to learn the deterministic 3rd token, then use it as eos
    r = served_lm.generate([7, 8, 9], max_new_tokens=6)
    toks = [p for k, p, _ in drain_events(r) if k == "token"]
    assert len(toks) == 6
    r = served_lm.generate([7, 8, 9], max_new_tokens=6, eos_id=toks[2])
    evs = drain_events(r)
    assert evs[-1][1]["finish_reason"] == "eos"
    assert [p for k, p, _ in evs if k == "token"] == toks[:2]
    # sampled run stays in-vocab and honors the token budget
    r = served_lm.generate([7, 8, 9], max_new_tokens=5, temperature=1.3,
                           top_k=5)
    toks = [p for k, p, _ in drain_events(r) if k == "token"]
    assert len(toks) == 5 and all(0 <= t < 48 for t in toks)


def test_generation_caps_at_max_context(served_lm):
    """max_tokens beyond the KV capacity is clamped server-side; the
    stream ends cleanly at the context cap, never a crash."""
    prompt = list(range(28))                  # 28 + budget vs ctx 32
    r = served_lm.generate(prompt, max_new_tokens=500)
    evs = drain_events(r)
    toks = [p for k, p, _ in evs if k == "token"]
    assert evs[-1][0] == "done"
    assert len(toks) == 32 - 28               # clamped to remaining room


def test_join_queue_overload_raises_429_shape(served_lm):
    """Saturate both slots with long generations, then overfill the join
    queue — admission control must answer ServerOverloadedError, not
    queue unboundedly."""
    live = [served_lm.generate([1], max_new_tokens=40, temperature=0.5)
            for _ in range(2)]
    with pytest.raises(ServerOverloadedError):
        for _ in range(16):                   # queue_limit is 8
            live.append(served_lm.generate([1], max_new_tokens=40))
    for r in live:
        r.cancel()
        drain_events(r, timeout=60)


def test_invalid_prompts_rejected(served_lm):
    with pytest.raises(ValueError):
        served_lm.generate([], max_new_tokens=2)
    with pytest.raises(ValueError):
        served_lm.generate([999], max_new_tokens=2)
    with pytest.raises(ValueError):
        served_lm.generate(list(range(32)), max_new_tokens=2)  # no room


def test_oversubscribed_pool_stall_releases_on_cancel():
    """All slots page-stalled on a dry pool must still honor
    cancellation — releasing a stalled slot is what refills the pool, so
    ignoring cancel here would deadlock the servable forever."""
    lm = ServedLM("stall-lm", load_servable(ZOO_SRC), ZOO_SRC,
                  decode=DecodeConfig(slots=2, page_size=8,
                                      pool_pages=5))   # 4 usable pages
    try:
        reqs = [lm.generate([1] * 8, max_new_tokens=500, temperature=0.5)
                for _ in range(2)]
        # both sequences grow until the pool is dry and every slot stalls
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and \
                lm.scheduler.admitting_engine().cache.free_pages() > 0:
            time.sleep(0.02)
        assert lm.scheduler.admitting_engine().cache.free_pages() == 0
        stalls = monitor.counter("serving_decode_page_stalls_total", "x",
                                 labels=("model",))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                stalls.value(model="stall-lm") == 0:
            time.sleep(0.02)
        assert stalls.value(model="stall-lm") > 0
        for r in reqs:
            r.cancel()
        evs = [drain_events(r, timeout=30) for r in reqs]
        assert all(e[-1][0] == "done" for e in evs)
        # slots and pages all came back — the pool is usable again
        assert lm.scheduler.admitting_engine().cache.free_pages() == 4
        r = lm.generate([1, 2], max_new_tokens=2)
        assert drain_events(r)[-1][0] == "done"
    finally:
        lm.shutdown(drain=False, timeout=5)


def test_swap_to_shorter_context_stays_safe():
    """A swap that shrinks KV capacity (cfg.max_context derives from the
    model) must update validation, not strand the scheduler."""
    lm = ServedLM("shrink-lm", load_servable(ZOO_SRC), ZOO_SRC,
                  decode=DecodeConfig(slots=2, page_size=8))
    try:
        assert lm.max_context == 32
        lm.swap(ZOO_SRC.replace("seq_length=32", "seq_length=16"))
        assert lm.max_context == 16
        with pytest.raises(ValueError):
            lm.generate(list(range(20)), max_new_tokens=2)
        r = lm.generate([1, 2, 3], max_new_tokens=3)
        evs = drain_events(r)
        assert evs[-1][0] == "done" and evs[-1][1]["version"] == 2
    finally:
        lm.shutdown(drain=False, timeout=5)


def test_deploy_kind_collision_is_loud():
    registry = ModelRegistry()
    registry.deploy_lm("m", ZOO_SRC,
                       decode=DecodeConfig(slots=2, page_size=8))
    with pytest.raises(ModelLoadError):
        registry.deploy("m", "zoo:LeNet", buckets=(1,))
    registry.undeploy("m", drain=False)
    registry.deploy("m", "zoo:LeNet", buckets=(1,))
    with pytest.raises(ModelLoadError):
        registry.deploy_lm("m", ZOO_SRC)
    registry.shutdown(drain=False)


# -------------------------------------- prefix cache + chunked prefill
def _greedy(lm, prompt, n=8):
    """(tokens, done-info) for one greedy generation."""
    req = lm.generate(prompt, max_new_tokens=n)
    evs = drain_events(req)
    assert evs[-1][0] == "done", evs[-1]
    return [p for k, p, _ in evs if k == "token"], evs[-1][1]


@pytest.fixture(scope="module")
def parity_lms():
    """The same model behind three decode configs: prefix cache on
    (default), prefix cache off, and cache off + tiny chunk budget."""
    net_src = ZOO_SRC
    lms = {
        "on": ServedLM("par-on", load_servable(net_src), net_src,
                       decode=DecodeConfig(slots=2, page_size=8)),
        "off": ServedLM("par-off", load_servable(net_src), net_src,
                        decode=DecodeConfig(slots=2, page_size=8,
                                            prefix_cache=False)),
        "chunk": ServedLM("par-chunk", load_servable(net_src), net_src,
                          decode=DecodeConfig(slots=2, page_size=8,
                                              prefix_cache=False,
                                              prefill_chunk_tokens=8)),
    }
    yield lms
    for lm in lms.values():
        lm.shutdown(drain=False, timeout=5)


def test_prefix_cache_greedy_parity_cold_hot_and_cow(parity_lms):
    """THE parity contract: greedy tokens bitwise-identical with the
    prefix cache on vs off — cold (miss), hot (shared-prefix hit), and
    the copy-on-write divergence case (page-aligned fully-cached
    prompt). The cache may only change WHERE KV comes from, never what
    gets sampled."""
    on, off = parity_lms["on"], parity_lms["off"]
    prefix = list(range(16))                      # 2 full pages
    # cold: identical programs either way, nothing cached yet
    t_on, i_on = _greedy(on, prefix + [17, 18, 19])
    t_off, i_off = _greedy(off, prefix + [17, 18, 19])
    assert t_on == t_off
    assert i_on["cached_tokens"] == 0 and i_off["cached_tokens"] == 0
    # hot: same prefix, divergent suffix -> 16 tokens of KV reused
    t_on, i_on = _greedy(on, prefix + [20, 21])
    t_off, _ = _greedy(off, prefix + [20, 21])
    assert t_on == t_off, (t_on, t_off)
    assert i_on["cached_tokens"] == 16
    # COW: page-aligned fully-cached prompt — the forced last-token
    # recompute diverges onto a private page copy
    t_on, i_on = _greedy(on, prefix)
    t_off, _ = _greedy(off, prefix)
    assert t_on == t_off, (t_on, t_off)
    assert i_on["cached_tokens"] == 15            # prompt_len - 1


def test_chunked_prefill_parity_and_chunk_accounting(parity_lms):
    """Chunking on vs off: identical greedy tokens, and the done event
    reports the budgeted chunk count (20-token prompt / 8-token budget
    -> 3 chunks)."""
    off, chunk = parity_lms["off"], parity_lms["chunk"]
    prompt = list(np.random.RandomState(5).randint(0, 48, 20))
    t_c, i_c = _greedy(chunk, prompt)
    t_o, i_o = _greedy(off, prompt)
    assert t_c == t_o, (t_c, t_o)
    assert i_c["prefill_chunks"] == 3             # 8 + 8 + 4
    assert i_o["prefill_chunks"] == 1             # whole prompt, one shot


def test_chunked_and_cow_traffic_never_compiles_on_request_path(
        parity_lms):
    """compiles == warmups per model AFTER hot/COW/chunked traffic: the
    chunk ladder and the COW copy were all AOT-warmed, so none of the
    new code paths paid for XLA on a live stream."""
    def fam_sum(family, model):
        total = 0.0
        for line in monitor.prometheus_text().splitlines():
            if line.startswith(family + "{") and f'model="{model}"' in line:
                total += float(line.rsplit(" ", 1)[1])
        return total

    for model in ("par-on", "par-off", "par-chunk"):
        csum = fam_sum("serving_decode_compiles_total", model)
        wsum = fam_sum("serving_decode_warmup_runs_total", model)
        assert csum == wsum and csum > 0, (model, csum, wsum)


def test_burst_admissions_drain_queue_in_one_tick():
    """When several slots free in one token step, the next admission
    pass must drain the join queue until slots or queue are exhausted —
    not trickle one admission per step. Driven tick-by-tick (no
    scheduler thread) so the assertion is on a single _admit pass."""
    eng = DecodeEngine(load_servable(ZOO_SRC),
                       DecodeConfig(slots=4, page_size=8), name="burst")
    eng.warm()
    sched = DecodeScheduler("burst", queue_limit=16)
    sched._started = True                 # keep the loop thread off
    sched.install(eng, version=1)
    reqs = [GenerateRequest([1, 2, 3], max_new_tokens=1)
            for _ in range(6)]
    for r in reqs:
        sched.submit(r)
    run = sched._runs[-1]
    assert sched._admit() is True
    # ONE pass filled every free slot from the queue
    assert len(run.prefill) == 4
    assert sched.queue_state()[0] == 2
    # prefill completes all four; max_new_tokens=1 finishes them at the
    # first token, freeing all four slots within the same tick
    assert sched._prefill_tick() is True
    assert len(run.prefill) == 0 and len(run.slot_req) == 0
    # the next pass admits the whole remainder at once
    assert sched._admit() is True
    assert len(run.prefill) == 2 and sched.queue_state()[0] == 0
    sched._prefill_tick()
    for r in reqs:
        assert r.done.is_set() and r.finish_reason == "length"
    sched._stop.set()
    eng.close()


def test_prefill_budget_caps_tokens_per_tick():
    """The per-tick prefill budget bounds how much prefill runs between
    decode steps: a 24-token prompt under an 8-token budget takes three
    ticks, one page-aligned chunk each — the head-of-line guarantee an
    in-flight stream's ITL rests on."""
    eng = DecodeEngine(load_servable(ZOO_SRC),
                       DecodeConfig(slots=2, page_size=8,
                                    prefill_chunk_tokens=8),
                       name="budget")
    eng.warm()
    sched = DecodeScheduler("budget", queue_limit=4)
    sched._started = True
    sched.install(eng, version=1)
    req = GenerateRequest(list(range(24)), max_new_tokens=2)
    sched.submit(req)
    assert sched._admit() is True
    run = sched._runs[-1]
    job = next(iter(run.prefill.values()))
    for expect_pos in (8, 16, 24):
        sched._prefill_tick()
        assert job.pos == expect_pos
    assert not run.prefill and len(run.slot_req) == 1
    assert req.n_emitted == 1                     # first token delivered
    sched._step_all()
    assert req.done.is_set()
    sched._stop.set()
    eng.close()


# ----------------------------------------------------------- HTTP + swap
@pytest.fixture(scope="module")
def lm_server():
    registry = ModelRegistry()
    registry.deploy_lm("lm", ZOO_SRC,
                       decode=DecodeConfig(slots=2, page_size=8))
    server = ModelServer(registry, port=0, default_deadline_s=60.0)
    yield server, registry
    server.drain(timeout=10)


def _gen(url, payload, headers=None, timeout=60):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    return urllib.request.urlopen(urllib.request.Request(
        url + "/v1/models/lm/generate", data=json.dumps(payload).encode(),
        headers=h), timeout=timeout)


def test_http_sse_stream_and_json(lm_server):
    server, _ = lm_server
    r = _gen(server.url, {"prompt": [1, 2, 3], "max_tokens": 5})
    assert r.status == 200
    assert r.headers.get("Content-Type") == "text/event-stream"
    events = [json.loads(line[6:]) for line in r
              if line.startswith(b"data: ")]
    toks = [e["token"] for e in events if "token" in e]
    assert len(toks) == 5
    assert events[-1]["done"] and events[-1]["finish_reason"] == "length"
    # buffered JSON answer carries the same tokens (greedy = determinism)
    r = _gen(server.url, {"prompt": [1, 2, 3], "max_tokens": 5,
                          "stream": False})
    doc = json.loads(r.read())
    assert doc["tokens"] == toks
    assert doc["finish_reason"] == "length"
    assert doc["ttft_ms"] is not None


def test_http_generate_error_mapping(lm_server):
    server, registry = lm_server
    # bad prompt -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _gen(server.url, {"prompt": [9999]})
    assert e.value.code == 400
    # unknown model -> 404
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            server.url + "/v1/models/nope/generate", data=b"{}",
            headers={"Content-Type": "application/json"}), timeout=10)
    assert e.value.code == 404
    # generate against a predict servable -> 400 with a pointed message
    registry.deploy("lenet", "zoo:LeNet", buckets=(1,))
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            server.url + "/v1/models/lenet/generate",
            data=json.dumps({"prompt": [1]}).encode(),
            headers={"Content-Type": "application/json"}), timeout=30)
    assert e.value.code == 400
    assert "predict servable" in json.loads(e.value.read())["error"]


def test_http_rolling_swap_mid_stream(lm_server):
    """A stream started on v1 finishes on v1 while the swap warms and
    flips admissions to v2; the next stream answers v2. Compile ledger
    stays balanced across the swap."""
    server, _ = lm_server
    r1 = _gen(server.url, {"prompt": [2, 4], "max_tokens": 30,
                           "temperature": 0.5})
    assert r1.headers.get("X-Model-Version") == "1"
    first = r1.readline()                     # stream is live
    assert first.startswith(b"data: ")
    swap = urllib.request.urlopen(urllib.request.Request(
        server.url + "/v1/models/lm/swap",
        data=json.dumps({"source": ZOO_SRC + "&seed=99"}).encode(),
        headers={"Content-Type": "application/json"}), timeout=300)
    assert swap.status == 200
    # v1 stream still completes cleanly after the swap
    tail = [json.loads(line[6:]) for line in r1
            if line.startswith(b"data: ")]
    assert tail[-1].get("done"), tail[-1]
    r2 = _gen(server.url, {"prompt": [2, 4], "max_tokens": 3})
    assert r2.headers.get("X-Model-Version") == "2"
    [_ for _ in r2]

    def fam_sum(family):
        total = 0.0
        for line in monitor.prometheus_text().splitlines():
            if line.startswith(family + "{") and 'model="lm"' in line:
                total += float(line.rsplit(" ", 1)[1])
        return total

    csum = fam_sum("serving_decode_compiles_total")
    wsum = fam_sum("serving_decode_warmup_runs_total")
    assert csum == wsum and csum > 0


def test_vocab_mismatch_swap_rejected(lm_server):
    server, _ = lm_server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            server.url + "/v1/models/lm/swap",
            data=json.dumps({"source": ZOO_SRC.replace(
                "vocab_size=48", "vocab_size=64")}).encode(),
            headers={"Content-Type": "application/json"}), timeout=300)
    assert e.value.code == 400


def test_http_concurrent_streams_zero_errors(lm_server):
    server, _ = lm_server
    errors, tokens = [], []

    def worker(i):
        try:
            r = _gen(server.url, {"prompt": [i % 48, 1], "max_tokens": 6,
                                  "temperature": 0.9, "top_k": 4})
            evs = [json.loads(line[6:]) for line in r
                   if line.startswith(b"data: ")]
            if not evs or not evs[-1].get("done"):
                errors.append((i, "truncated"))
            tokens.append(sum(1 for e in evs if "token" in e))
        except Exception as e:              # noqa: BLE001
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert all(n == 6 for n in tokens), tokens


# ---------------------------------------------------------- fleet/router
@pytest.mark.slow
def test_router_streams_through_inprocess_fleet():
    from deeplearning4j_tpu.serving.fleet import (
        InProcessReplica, ReplicaSpec, ReplicaSupervisor,
    )
    from deeplearning4j_tpu.serving.router import (
        ResilientRouter, RouterServer,
    )
    spec = ReplicaSpec([], lms=[("lm", ZOO_SRC)],
                       decode=DecodeConfig(slots=2, page_size=8))
    sup = ReplicaSupervisor(
        lambda i: InProcessReplica(f"replica-{i}", spec), 2)
    sup.start()
    router = ResilientRouter(sup.healthy)
    server = RouterServer(router, supervisor=sup)
    try:
        r = _gen(server.url, {"prompt": [1, 2], "max_tokens": 4},
                 headers={"X-Priority": "interactive"})
        assert r.status == 200
        assert r.headers.get("X-Served-By", "").startswith("replica-")
        evs = [json.loads(line[6:]) for line in r
               if line.startswith(b"data: ")]
        assert sum(1 for e in evs if "token" in e) == 4
        assert evs[-1].get("done")
        # stream metering is its own family
        streams = monitor.counter(
            "serving_router_stream_requests_total", "x",
            labels=("model", "code", "cls"))
        assert streams.value(model="lm", code="200",
                             cls="standard") >= 1 \
            or streams.value(model="lm", code="200",
                             cls="interactive") >= 1
    finally:
        sup.stop()
        server.stop()


# ------------------------------------------------------ the smoke (slow)
@pytest.mark.slow
def test_decode_smoke_gate(tmp_path):
    """tools/decode_smoke.py end-to-end: N concurrent streams through a
    mid-traffic hot-swap, zero 5xx, ledger equality, variant quality —
    asserted by the tool itself (exit 0 == contract held)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "DECODE_test.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "decode_smoke.py"),
         "--streams", "3", "--requests", "9", "--max-new-tokens", "12",
         "--n-layers", "1", "--n-embd", "64", "--seq-length", "64",
         "--vocab", "128", "--out", str(out)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    doc = json.loads(out.read_text())
    assert doc["ok"] and doc["sweep"][0]["zero_5xx"]
    assert doc["sweep"][0]["decode_tokens_sec"] > 0
    # prefix-cache + chunked-prefill acceptance, re-asserted here so the
    # gate fails loudly even if the tool's own failure list regresses:
    # the cache engaged, compiles==warmups held WITH chunking enabled,
    # hot TTFT >= 2x better than cold, chunking improved interferer ITL
    assert doc["prefix_loadgen"]["prefix"]["cache_hit_rate"] > 0
    assert doc["kv_cache"]["hits"] > 0
    assert doc["ledger"]["compiles"] == doc["ledger"]["warmups"] > 0
    assert doc["prefix_ttft"]["hot_p99_ms"] * 2 \
        <= doc["prefix_ttft"]["cold_p99_ms"]
    assert doc["interferer_itl"]["chunked_p99_ms"] \
        < doc["interferer_itl"]["nochunk_p99_ms"]
