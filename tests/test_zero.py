"""ZeRO / FSDP sharding (`parallel/zero.py` + ParallelWrapper zero_stage).

No DL4J analog (reference DP always keeps full per-worker copies —
ParallelWrapper.java:467-579); this is TPU-native capability. Semantics
contract: ZeRO is a memory layout, not an algorithm change — stage 1 and
stage 3 must produce the same trained parameters as plain SYNC_GRADIENTS
up to reduction-order epsilon, while the optimizer state (and at stage 3
the parameters) live dim-0-sharded over the "data" axis during training.
"""
import jax
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import (
    ParallelWrapper, TrainingMode, build_mesh, MeshConfig, sharded_fraction,
)
from deeplearning4j_tpu.parallel.zero import zero_spec


def _blob_data(n=256, k=4, d=8, seed=0):
    rs = np.random.RandomState(seed)
    X = np.vstack([rs.randn(n // k, d) * 0.35 + i for i in range(k)]
                  ).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    perm = rs.permutation(n)
    return X[perm], Y[perm]


def _mlp(seed=7, lr=5e-2, width=16):
    return (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(lr))
            .list()
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())


def _fit(zero_stage, epochs=3, seed=3, lr=1e-2):
    X, Y = _blob_data()
    net = MultiLayerNetwork(_mlp(seed=seed, lr=lr)).init()
    w = ParallelWrapper(net, mode=TrainingMode.SYNC_GRADIENTS,
                        zero_stage=zero_stage)
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=epochs)
    return net, w, (X, Y)


def test_zero_spec_divisibility():
    a = np.zeros((16, 3))
    b = np.zeros((6, 3))     # 6 % 8 != 0 -> replicated
    c = np.zeros(())
    assert zero_spec(a, 8) == P("data")
    assert zero_spec(b, 8) == P()
    assert zero_spec(c, 8) == P()


def test_zero_stage_validation():
    net = MultiLayerNetwork(_mlp()).init()
    with pytest.raises(ValueError):
        ParallelWrapper(net, zero_stage=2)
    with pytest.raises(ValueError):
        ParallelWrapper(net, mode=TrainingMode.AVERAGING, zero_stage=1)


def test_zero1_matches_plain_sync():
    """Stage 1 is the same algorithm as SYNC_GRADIENTS — trained params
    must match to reduction-order epsilon."""
    net_ref, _, _ = _fit(zero_stage=0)
    net_z1, _, _ = _fit(zero_stage=1)
    np.testing.assert_allclose(np.asarray(net_ref.params_flat()),
                               np.asarray(net_z1.params_flat()),
                               atol=2e-5, rtol=1e-4)


def test_zero3_matches_plain_sync_and_trains():
    net_ref, _, _ = _fit(zero_stage=0)
    net_z3, _, _ = _fit(zero_stage=3)
    np.testing.assert_allclose(np.asarray(net_ref.params_flat()),
                               np.asarray(net_z3.params_flat()),
                               atol=2e-5, rtol=1e-4)
    # convergence on its own terms (enough epochs to separate the blobs)
    net, _, data = _fit(zero_stage=3, epochs=8, seed=7, lr=5e-2)
    acc = net.evaluate(data).accuracy()
    assert acc > 0.9, acc


def test_zero1_opt_state_is_sharded_in_training():
    """During (and after) fit, divisible optimizer-state leaves live split
    8 ways over the data axis: each device holds 1/8 of dim 0."""
    net, w, _ = _fit(zero_stage=1, epochs=1)
    mesh = w.mesh
    n = mesh.shape["data"]
    checked = 0
    for leaf in jax.tree_util.tree_leaves(net.opt_state):
        if zero_spec(leaf, n) == P("data"):
            shard = leaf.addressable_shards[0].data
            assert shard.shape[0] == leaf.shape[0] // n, \
                (leaf.shape, shard.shape)
            checked += 1
    assert checked >= 2   # Adam mu+nu for at least the kernel
    # params stay replicated at stage 1
    for leaf in jax.tree_util.tree_leaves(net.params):
        assert leaf.addressable_shards[0].data.shape == leaf.shape


def test_zero3_params_sharded_in_training_gathered_after():
    """Stage 3: params live sharded inside the fit loop (checked via the
    wrapper's placement hook), and come back whole after fit so
    eval/serialization see full arrays."""
    X, Y = _blob_data()
    net = MultiLayerNetwork(_mlp(seed=3, lr=1e-2)).init()
    w = ParallelWrapper(net, zero_stage=3)
    w._zero_place()
    n = w.mesh.shape["data"]
    sharded = [leaf for leaf in jax.tree_util.tree_leaves(net.params)
               if zero_spec(leaf, n) == P("data")]
    assert sharded, "no divisible param leaf found"
    for leaf in sharded:
        assert leaf.addressable_shards[0].data.shape[0] \
            == leaf.shape[0] // n
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=1)
    for leaf in jax.tree_util.tree_leaves(net.params):
        assert leaf.addressable_shards[0].data.shape == leaf.shape


def test_sharded_fraction_diagnostic():
    net, w, _ = _fit(zero_stage=1, epochs=1)
    frac = sharded_fraction(net.opt_state, w.mesh)
    # Adam on an 8->16->4 MLP: every kernel and bias has dim0 % 8 == 0
    # except the 4-wide output bias; the bulk of the bytes shard.
    assert frac > 0.5, frac


def test_zero_on_computation_graph():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    X, Y = _blob_data()
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(11)
                      .updater(Adam(5e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(8)))
    g.add_layer("h", DenseLayer(n_out=16, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"), "h")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    w = ParallelWrapper(net, zero_stage=3)
    w.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=16)
    acc = net.evaluate((X, Y)).accuracy()
    assert acc > 0.9, acc
