"""End-to-end MultiLayerNetwork tests: the minimum slice of SURVEY.md §7
build order — config -> init -> fit -> eval -> serialize."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    InputType, MultiLayerConfiguration, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    LSTM, BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
    RnnOutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.train.listeners import CollectScoresIterationListener
from deeplearning4j_tpu.util.serialization import (
    load_model, restore_multilayer_network, save_model,
)


def make_blobs(n=256, nc=3, nf=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(nc, nf)) * 4
    X, Y = [], []
    for c in range(nc):
        X.append(rng.normal(size=(n // nc, nf)) + centers[c])
        y = np.zeros((n // nc, nc))
        y[:, c] = 1
        Y.append(y)
    X = np.concatenate(X).astype(np.float32)
    Y = np.concatenate(Y).astype(np.float32)
    idx = rng.permutation(len(X))
    return X[idx], Y[idx]


def mlp_conf(nf=4, nc=3, updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(42)
            .updater(updater or Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=nc, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(nf))
            .build())


class TestMLP:
    def test_fit_reduces_score_and_learns(self):
        X, Y = make_blobs()
        net = MultiLayerNetwork(mlp_conf()).init()
        scores = CollectScoresIterationListener()
        net.set_listeners(scores)
        net.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=30)
        first = scores.scores[0][1]
        last = scores.scores[-1][1]
        assert last < first * 0.5, f"loss did not drop: {first} -> {last}"
        ev = net.evaluate(ArrayDataSetIterator(X, Y, batch_size=64))
        assert ev.accuracy() > 0.9

    def test_output_shape_and_softmax(self):
        X, Y = make_blobs(n=30)
        net = MultiLayerNetwork(mlp_conf()).init()
        out = net.output(X)
        assert out.shape == (30, 3)
        np.testing.assert_allclose(np.sum(np.asarray(out), axis=1),
                                   np.ones(30), rtol=1e-5)

    def test_feed_forward_collects_all_activations(self):
        X, _ = make_blobs(n=16)
        net = MultiLayerNetwork(mlp_conf()).init()
        acts = net.feed_forward(X[:4])
        assert len(acts) == 3
        assert acts[0].shape == (4, 32)
        assert acts[-1].shape == (4, 3)

    def test_num_params(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        # 4*32+32 + 32*32+32 + 32*3+3 = 160 + 1056 + 99
        assert net.num_params() == 4 * 32 + 32 + 32 * 32 + 32 + 32 * 3 + 3

    def test_params_flat_roundtrip(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        flat = net.params_flat()
        assert flat.shape == (net.num_params(),)
        X, _ = make_blobs(n=16)
        before = np.asarray(net.output(X[:4]))
        net.set_params_flat(flat)
        after = np.asarray(net.output(X[:4]))
        np.testing.assert_allclose(before, after, rtol=1e-6)

    def test_l2_regularization_increases_score(self):
        X, Y = make_blobs(n=64)
        conf_plain = mlp_conf()
        conf_l2 = (NeuralNetConfiguration.Builder().seed(42).updater(Adam(1e-2))
                   .l2(0.1).list()
                   .layer(DenseLayer(n_out=32, activation="relu"))
                   .layer(DenseLayer(n_out=32, activation="relu"))
                   .layer(OutputLayer(n_out=3))
                   .set_input_type(InputType.feed_forward(4)).build())
        ds = DataSet(X, Y)
        n1 = MultiLayerNetwork(conf_plain).init()
        n2 = MultiLayerNetwork(conf_l2).init()
        assert n2.score(ds) > n1.score(ds)


class TestCNN:
    def test_lenet_slice_trains(self):
        """Minimum end-to-end slice: LeNet-style CNN on synthetic 'MNIST'
        (SURVEY.md §7 build order step 3; reference LeNet.java:83-95)."""
        rng = np.random.default_rng(0)
        n, nc = 128, 4
        X = rng.normal(size=(n, 12, 12, 1)).astype(np.float32)
        # separable-by-class data: class = quadrant with max energy
        labels = np.argmax([
            np.abs(X[:, :6, :6, 0]).sum((1, 2)),
            np.abs(X[:, :6, 6:, 0]).sum((1, 2)),
            np.abs(X[:, 6:, :6, 0]).sum((1, 2)),
            np.abs(X[:, 6:, 6:, 0]).sum((1, 2))], axis=0)
        Y = np.eye(nc, dtype=np.float32)[labels]
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(3e-3))
                .list()
                .layer(ConvolutionLayer(n_out=8, kernel=(3, 3),
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=16, kernel=(3, 3),
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=nc))
                .set_input_type(InputType.convolutional(12, 12, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        s = CollectScoresIterationListener()
        net.set_listeners(s)
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=20)
        assert s.scores[-1][1] < s.scores[0][1] * 0.7

    def test_batchnorm_in_net(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 8, 8, 2)).astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(1e-2))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel=(3, 3),
                                        convolution_mode="same"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(8, 8, 2))
                .build())
        net = MultiLayerNetwork(conf).init()
        state_before = np.asarray(net.state["1"]["mean"]).copy()
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=2)
        state_after = np.asarray(net.state["1"]["mean"])
        assert not np.allclose(state_before, state_after), \
            "BN running stats must update during fit"


class TestRnnNet:
    def _seq_data(self, n=64, t=6, f=3, nc=2, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, t, f)).astype(np.float32)
        labels = (X.sum((1, 2)) > 0).astype(int)
        Y = np.tile(np.eye(nc, dtype=np.float32)[labels][:, None, :], (1, t, 1))
        return X, Y

    def test_lstm_net_trains(self):
        X, Y = self._seq_data()
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2))
                .set_input_type(InputType.recurrent(3, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        s = CollectScoresIterationListener()
        net.set_listeners(s)
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=15)
        assert s.scores[-1][1] < s.scores[0][1]

    def test_tbptt_matches_epochs(self):
        X, Y = self._seq_data(t=8)
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2))
                .set_input_type(InputType.recurrent(3, 8))
                .tbptt(4)
                .build())
        net = MultiLayerNetwork(conf).init()
        s = CollectScoresIterationListener()
        net.set_listeners(s)
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=5)
        # 2 batches * 2 chunks * 5 epochs = 20 iterations
        assert net.iteration_count == 20
        assert s.scores[-1][1] < s.scores[0][1]

    def test_rnn_time_step_stateful(self):
        X, Y = self._seq_data(n=4, t=6)
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2))
                .set_input_type(InputType.recurrent(3, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        full = np.asarray(net.output(X))
        net.rnn_clear_previous_state()
        outs = []
        for t in range(6):
            outs.append(np.asarray(net.rnn_time_step(X[:, t, :])))
        stepped = np.stack(outs, axis=1)
        np.testing.assert_allclose(stepped, full, rtol=1e-4, atol=1e-5)

    def test_masked_training_runs(self):
        X, Y = self._seq_data(t=6)
        mask = np.ones((64, 6), np.float32)
        mask[:, 4:] = 0
        it = ArrayDataSetIterator(X, Y, batch_size=32, features_mask=mask,
                                  labels_mask=mask)
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2))
                .set_input_type(InputType.recurrent(3, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=2)
        assert np.isfinite(net.score())


class TestSerde:
    def test_conf_json_roundtrip(self):
        conf = mlp_conf(updater=Nesterovs(learning_rate=0.05, momentum=0.8))
        j = conf.to_json()
        back = MultiLayerConfiguration.from_json(j)
        assert back == conf

    def test_model_zip_roundtrip(self, tmp_path):
        X, Y = make_blobs(n=64)
        net = MultiLayerNetwork(mlp_conf()).init()
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=3)
        path = str(tmp_path / "model.zip")
        save_model(net, path)
        restored = restore_multilayer_network(path)
        np.testing.assert_allclose(np.asarray(net.output(X[:8])),
                                   np.asarray(restored.output(X[:8])),
                                   rtol=1e-5)
        assert restored.iteration_count == net.iteration_count

    def test_training_resumes_identically(self, tmp_path):
        """Checkpoint must capture updater state: resume == uninterrupted
        (ModelSerializer updaterState.bin semantics)."""
        X, Y = make_blobs(n=64)
        it = lambda: ArrayDataSetIterator(X, Y, batch_size=32)
        netA = MultiLayerNetwork(mlp_conf()).init()
        netA.fit(it(), epochs=2)
        path = str(tmp_path / "ckpt.zip")
        save_model(netA, path)
        netA.fit(it(), epochs=2)

        netB = load_model(path)
        netB.fit(it(), epochs=2)
        np.testing.assert_allclose(np.asarray(netA.params_flat()),
                                   np.asarray(netB.params_flat()),
                                   rtol=1e-4, atol=1e-6)

    def test_frozen_layer_params_do_not_move(self):
        import dataclasses as dc
        X, Y = make_blobs(n=64)
        conf = (NeuralNetConfiguration.Builder().seed(42).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation="relu", frozen=True))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        w_before = np.asarray(net.params["0"]["W"]).copy()
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=3)
        np.testing.assert_allclose(np.asarray(net.params["0"]["W"]), w_before)
        assert not np.allclose(np.asarray(net.params["1"]["W"]),
                               np.asarray(MultiLayerNetwork(conf).init().params["1"]["W"]))


def test_summary_tables():
    """summary() prints the layer/vertex table (MultiLayerNetwork.java:3230)."""
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    net = MultiLayerNetwork(LeNet(num_classes=10).conf()).init()
    s = net.summary()
    assert "ConvolutionLayer" in s and "total parameters" in s
    assert f"{net.num_params():,}" in s
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(0)
                      .updater(Adam(1e-3)))
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(6)))
    g.add_layer("d", DenseLayer(n_out=6, activation="tanh"), "in")
    g.add_vertex("res", ElementWiseVertex(op="add"), "d", "in")
    g.add_layer("out", OutputLayer(n_out=2), "res")
    g.set_outputs("out")
    gn = ComputationGraph(g.build()).init()
    sg = gn.summary()
    assert "res" in sg and "ElementWiseVertex" in sg
    assert f"{gn.num_params():,}" in sg


class TestScanFit:
    """Input-pipelined fit (scan_steps>1) must be bit-identical to the
    per-call path: same RNG stream, same update math, same listener calls."""

    def test_scan_fit_matches_per_call_bitwise(self):
        X, Y = make_blobs(n=250)        # 250/64 -> ragged tail batch of 58
        a = MultiLayerNetwork(mlp_conf()).init()
        b = MultiLayerNetwork(mlp_conf()).init()
        sa, sb = CollectScoresIterationListener(), CollectScoresIterationListener()
        a.set_listeners(sa)
        b.set_listeners(sb)
        a.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=3)
        b.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=3,
              scan_steps=3)
        assert a.iteration_count == b.iteration_count
        np.testing.assert_array_equal(
            np.array([s for _, s in sa.scores]),
            np.array([s for _, s in sb.scores]))
        for k in a.params:
            for pk in a.params[k]:
                np.testing.assert_array_equal(
                    np.asarray(a.params[k][pk]), np.asarray(b.params[k][pk]),
                    err_msg=f"{k}/{pk}")

    def test_scan_fit_with_dropout_and_env_default(self, monkeypatch):
        X, Y = make_blobs(n=128)
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(1e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        a = MultiLayerNetwork(conf).init()
        b = MultiLayerNetwork(conf).init()
        a.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=2)
        monkeypatch.setenv("DL4J_TPU_SCAN_STEPS", "4")
        b.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=2)
        for k in a.params:
            for pk in a.params[k]:
                np.testing.assert_array_equal(
                    np.asarray(a.params[k][pk]), np.asarray(b.params[k][pk]))

    def test_scan_fit_falls_back_for_model_reading_listeners(self, tmp_path):
        from deeplearning4j_tpu.train.listeners import CheckpointListener
        X, Y = make_blobs(n=128)
        net = MultiLayerNetwork(mlp_conf()).init()
        ckpt = CheckpointListener(str(tmp_path), save_every_n_iterations=2)
        net.set_listeners(ckpt)
        net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=1,
                scan_steps=4)
        # per-call fallback: checkpoints reflect the exact iteration params
        assert len(ckpt._saved) >= 1
        assert net.iteration_count == 3   # 126 samples, drop_last batching


class TestScanStepsDefault:
    def test_cpu_default_is_per_call(self, monkeypatch):
        from deeplearning4j_tpu.nn.multilayer import _default_scan_steps
        monkeypatch.delenv("DL4J_TPU_SCAN_STEPS", raising=False)
        # conftest pins the cpu backend; per-call is the measured CPU
        # winner (PERF.md: conv-in-scan 10.9x slower on XLA:CPU)
        assert _default_scan_steps() == 1

    def test_env_override_wins(self, monkeypatch):
        from deeplearning4j_tpu.nn.multilayer import _default_scan_steps
        monkeypatch.setenv("DL4J_TPU_SCAN_STEPS", "7")
        assert _default_scan_steps() == 7

    def test_tpu_default_is_scan10(self, monkeypatch):
        import deeplearning4j_tpu.nn.multilayer as ml
        monkeypatch.delenv("DL4J_TPU_SCAN_STEPS", raising=False)
        monkeypatch.setattr(ml.jax, "default_backend", lambda: "tpu")
        assert ml._default_scan_steps() == 10

    def test_axon_tunnel_counts_as_tpu(self, monkeypatch):
        # the tunneled chip registers platform "axon" with device_kind
        # "TPU v5 lite" — flash/scan gating must recognize it as TPU
        import deeplearning4j_tpu.util.platform as plat

        class _Dev:
            platform = "axon"
            device_kind = "TPU v5 lite"

        monkeypatch.setattr(plat.jax, "default_backend", lambda: "axon")
        monkeypatch.setattr(plat.jax, "devices", lambda: [_Dev()])
        assert plat.is_tpu_backend() is True
        import deeplearning4j_tpu.nn.multilayer as ml
        monkeypatch.delenv("DL4J_TPU_SCAN_STEPS", raising=False)
        assert ml._default_scan_steps() == 10

    def test_cpu_is_not_tpu(self):
        import deeplearning4j_tpu.util.platform as plat
        assert plat.is_tpu_backend() is False   # conftest pins cpu


class TestGradientAccumulation:
    def _net(self, seed=21):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Sgd
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Sgd(1e-1)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())
        return MultiLayerNetwork(conf).init()

    def _data(self, n=64):
        rs = np.random.RandomState(3)
        X = rs.randn(n, 5).astype("float32")
        Y = np.eye(3, dtype="float32")[rs.randint(0, 3, n)]
        return X, Y

    def test_accumulation_equals_big_batch(self):
        # 4 micro-batches of 16 accumulated == one step on a batch of 64
        # (equal-size micro means == full-batch mean; BN-free net)
        X, Y = self._data(64)
        a = self._net()
        a.fit((X, Y), batch_size=16, accumulate_steps=4, epochs=2)
        b = self._net()
        b.fit((X, Y), batch_size=64, epochs=2)
        assert a.iteration_count == b.iteration_count == 2
        import jax
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-5, atol=1e-6)

    def test_ragged_tail_accumulates_with_correct_mean(self):
        # 6 micro-batches, K=4 -> chunks of 4 and 2 -> 2 optimizer steps,
        # equal to per-call steps on batches of 64 and 32
        X, Y = self._data(96)
        a = self._net()
        a.fit((X, Y), batch_size=16, accumulate_steps=4)
        assert a.iteration_count == 2
        b = self._net()
        b.fit((X[:64], Y[:64]), batch_size=64)
        b.fit((X[64:], Y[64:]), batch_size=32)
        import jax
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-5, atol=1e-6)

    def test_conflicting_modes_rejected(self):
        import pytest
        X, Y = self._data(32)
        net = self._net()
        with pytest.raises(ValueError, match="mutually exclusive"):
            net.fit((X, Y), batch_size=16, accumulate_steps=2,
                    scan_steps=2)

    def test_listener_sees_per_step_iterations(self):
        from deeplearning4j_tpu.train.listeners import (
            CollectScoresIterationListener)
        X, Y = self._data(64)
        net = self._net()
        lst = CollectScoresIterationListener()
        net.set_listeners(lst)
        net.fit((X, Y), batch_size=16, accumulate_steps=4, epochs=3)
        assert net.iteration_count == 3           # one step per chunk
        assert len(lst.scores) == 3

    def test_graph_accumulation_equals_big_batch(self):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.network import GraphBuilder
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        X, Y = self._data(64)

        def net():
            g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(9)
                              .updater(Sgd(1e-1)))
                 .add_inputs("in")
                 .set_input_types(InputType.feed_forward(5)))
            g.add_layer("d", DenseLayer(n_out=16, activation="tanh"), "in")
            g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "d")
            g.set_outputs("out")
            return ComputationGraph(g.build()).init()

        from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
        a = net()
        a.fit(ArrayDataSetIterator(X, Y, batch_size=16),
              accumulate_steps=4, epochs=2)
        b = net()
        b.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=2)
        assert a.iteration_count == b.iteration_count == 2
        import jax
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-5, atol=1e-6)

    def test_gradient_listener_gets_averaged_grads(self):
        # wants_gradients listeners receive the AVERAGED per-step grads
        # (lockstep callbacks — no one-chunk deferral on this path)
        class GradSpy:
            wants_gradients = True
            reads_model = True

            def __init__(self):
                self.calls = []

            def should_capture(self, it):
                return True

            def on_gradients(self, model, it, ep, grads, updates):
                self.calls.append(
                    (it, grads is not None and updates is not None))

            def __getattr__(self, name):
                return lambda *a, **k: None

        X, Y = self._data(64)
        net = self._net()
        spy = GradSpy()
        net.set_listeners(spy)
        net.fit((X, Y), batch_size=16, accumulate_steps=4, epochs=2)
        assert spy.calls == [(0, True), (1, True)]
