"""Continuous-rollout tests: RolloutController state machine, blessing
contract, load-signal autoscaling, canary-aware routing surfaces, and the
admin-race guard (serving/rollout.py + fleet.py + router.py).

Same determinism contract as test_fleet.py: fake clocks drive the
controller's poll/observe windows and the autoscaler's tick counters,
a fake wire pins every verdict input (per-replica /v1/slo, /v1/timeseries,
probe predicts), and every decision path is asserted without wall-clock
waits. The end-to-end drill (real train -> bless -> canary -> promote
under live traffic, plus a poisoned-checkpoint auto-rollback) lives in
tools/rollout_drill.py and rides as a slow-marked test here.
"""
import hashlib
import json
import os
import random
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import flight
from deeplearning4j_tpu.serving.fleet import (
    AutoscaleConfig, Replica, ReplicaSpec, ReplicaSupervisor,
)
from deeplearning4j_tpu.serving.rollout import (
    RolloutController, read_blessed,
)
from deeplearning4j_tpu.serving.router import ResilientRouter, RouterServer
from deeplearning4j_tpu.train.resilience import CheckpointManager


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeReplica(Replica):
    def __init__(self, name, spec=None):
        super().__init__(name, spec)
        self.probe_ok = True
        self.alive_flag = False
        self.launches = 0
        self.kills = 0
        self.stops = 0
        self.draining = False

    def launch(self):
        self.launches += 1
        self.alive_flag = True
        self.url = f"http://fake/{self.name}/{self.launches}"

    def alive(self):
        return self.alive_flag

    def kill(self):
        self.kills += 1
        self.alive_flag = False

    def stop(self):
        self.stops += 1
        self.alive_flag = False

    def begin_drain(self):
        self.draining = True
        self.probe_ok = False          # its own /readyz flips to 503


class FakeWire:
    """Transport fake: records swaps/rollbacks per replica and serves
    canned /v1/slo + /v1/timeseries verdict inputs."""

    def __init__(self):
        self.swaps = []                # (replica, source)
        self.rollbacks = []
        self.fail_swap_on = set()
        self.fail_rollback_on = set()
        self.slo = {}                  # replica -> doc
        self.ts = {}                   # replica -> doc
        self.predict_fn = None         # (replica, body) -> outputs row

    def __call__(self, replica, path, body, headers, timeout):
        def _json(doc, code=200):
            return code, {"Content-Type": "application/json"}, \
                json.dumps(doc).encode()
        if path.endswith("/swap"):
            src = json.loads(body)["source"]
            self.swaps.append((replica.name, src))
            if replica.name in self.fail_swap_on:
                return _json({"error": "load failed"}, code=500)
            return _json({"model": "m",
                          "active": {"version": 2, "source": src}})
        if path.endswith("/rollback"):
            self.rollbacks.append(replica.name)
            if replica.name in self.fail_rollback_on:
                return _json({"error": "no previous version"}, code=409)
            return _json({"model": "m",
                          "active": {"version": 1, "source": "/old/src"}})
        if path.endswith("/predict"):
            row = self.predict_fn(replica, json.loads(body))
            return _json({"model": "m", "version": 2, "outputs": [row]})
        if path == "/v1/slo":
            return _json(self.slo.get(replica.name, {"enabled": False}))
        if path.startswith("/v1/timeseries"):
            return _json(self.ts.get(replica.name, {"enabled": False}))
        if path == "/v1/debug/flight":
            return _json({"records": [
                {"trace_id": "t-slow", "duration_ms": 512.0},
                {"trace_id": "t-fast", "duration_ms": 4.0}]})
        return _json({"error": "not found"}, code=404)


def _healthy_stats(wire, names, p99=0.01, ratio=1.0, requests=200):
    # ratio is the /v1/slo availability objective's measured GOOD
    # fraction (1.0 = no errors), matching monitor/slo.py verdict()
    for n in names:
        wire.slo[n] = {"enabled": True, "state": "ok", "objectives": [
            {"name": "availability", "kind": "availability",
             "ratio": ratio}]}
        wire.ts[n] = {"enabled": True, "kind": "histogram",
                      "count": requests, "p99": p99}


def _fleet(n=3):
    spec = ReplicaSpec([("m", "/old/src")], lms=[("other-lm", "/lm/src")])
    reps = []
    for i in range(n):
        r = FakeReplica(f"r{i}", spec)
        r.launch()
        r.state = "ready"
        reps.append(r)

    class Sup:
        replicas = reps

        def healthy(self):
            return [r for r in self.replicas if r.state == "ready"]

    return Sup(), reps, spec


def _bless_dir(tmp_path, content=b"weights-v2", name="ckpt_000002.zip"):
    path = tmp_path / name
    path.write_bytes(content)
    doc = {"version": 1, "file": name, "path": str(path),
           "sha256": hashlib.sha256(content).hexdigest(),
           "blessed_at": 1.0, "metrics": {"accuracy": 0.97},
           "iteration": 42}
    (tmp_path / "blessed.json").write_text(json.dumps(doc))
    return str(path)


def _controller(tmp_path, sup, wire, clock, **kw):
    kw.setdefault("poll_interval_s", 1.0)
    kw.setdefault("observe_s", 10.0)
    kw.setdefault("min_canary_requests", 0)
    kw.setdefault("promote_stagger_s", 0.0)
    return RolloutController(
        sup, None, str(tmp_path), "m", transport=wire,
        time_fn=clock, wall_fn=clock, sleep_fn=lambda s: None, **kw)


# ------------------------------------------------------ blessing contract
def test_read_blessed_resolves_and_rejects_missing_file(tmp_path):
    assert read_blessed(str(tmp_path)) is None
    path = _bless_dir(tmp_path)
    doc = read_blessed(str(tmp_path))
    assert doc["path"] == path and doc["metrics"]["accuracy"] == 0.97
    os.remove(path)                      # blessed file vanished
    assert read_blessed(str(tmp_path)) is None


def test_checkpoint_manager_bless_writes_manifest(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    ckpt = tmp_path / "ckpt_000000.zip"
    ckpt.write_bytes(b"fake-zip")
    out = mgr.bless(str(ckpt), {"accuracy": 0.91})
    assert os.path.basename(out) == "blessed.json"
    doc = read_blessed(str(tmp_path))
    assert doc["file"] == "ckpt_000000.zip"
    assert doc["sha256"] == hashlib.sha256(b"fake-zip").hexdigest()
    assert doc["metrics"] == {"accuracy": 0.91}
    # re-blessing another checkpoint replaces the manifest atomically
    ckpt2 = tmp_path / "ckpt_000001.zip"
    ckpt2.write_bytes(b"fake-zip-2")
    mgr.bless(str(ckpt2))
    assert read_blessed(str(tmp_path))["file"] == "ckpt_000001.zip"


# -------------------------------------------------- canary -> promote
def test_canary_on_one_replica_then_fleet_promote(tmp_path):
    sup, reps, spec = _fleet(3)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock)
    assert rc.current_source == "/old/src"
    src = _bless_dir(tmp_path)
    rc.tick()
    # exactly ONE replica swapped, marked canary, admin surface held
    assert len(wire.swaps) == 1
    canary_name = wire.swaps[0][0]
    canary = next(r for r in reps if r.name == canary_name)
    assert canary.role == "canary" and canary.rollout_generation == 1
    assert rc.state == "canary" and rc.holds_admin()
    # healthy evidence on every replica -> promote at window end
    _healthy_stats(wire, [r.name for r in reps])
    clock.advance(10.1)
    rc.tick()
    assert rc.state == "idle" and not rc.holds_admin()
    assert rc.last_verdict["decision"] == "promoted"
    # the two incumbents were swapped too (staggered fan-out)
    assert sorted(n for n, _ in wire.swaps) == ["r0", "r1", "r2"]
    assert all(s == src for _, s in wire.swaps)
    # restart durability: the shared spec now names the promoted source
    assert spec.models == [("m", src)]
    assert spec.lms == [("other-lm", "/lm/src")]    # other models untouched
    assert all(r.role == "stable" for r in reps)
    assert rc.current_source == src
    # the decided identity is not re-canaried on the next poll
    clock.advance(2.0)
    rc.tick()
    assert len(wire.swaps) == 3 and rc.state == "idle"


def test_canary_needs_two_ready_replicas(tmp_path):
    sup, reps, _ = _fleet(1)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock)
    _bless_dir(tmp_path)
    rc.tick()
    # never canary the only serving replica
    assert wire.swaps == [] and rc.state == "idle"


# ------------------------------------------------ rejection -> rollback
def test_error_ratio_regression_rolls_back_with_postmortem(tmp_path):
    pm_dir = tmp_path / "pm"
    flight.enable_flight(capacity=64, dump_dir=str(pm_dir))
    try:
        sup, reps, spec = _fleet(3)
        wire = FakeWire()
        clock = FakeClock()
        rc = _controller(tmp_path, sup, wire, clock)
        src = _bless_dir(tmp_path)
        rc.tick()
        canary_name = wire.swaps[0][0]
        _healthy_stats(wire, [r.name for r in reps])
        # the canary burns error budget the incumbents don't
        wire.slo[canary_name]["objectives"][0]["ratio"] = 0.25
        clock.advance(10.1)
        rc.tick()
        assert rc.state == "idle"
        assert rc.last_verdict["decision"] == "rejected"
        assert rc.last_verdict["metric"] == "error_ratio"
        assert wire.rollbacks == [canary_name]
        assert spec.models == [("m", "/old/src")]   # spec never touched
        canary = next(r for r in reps if r.name == canary_name)
        assert canary.role == "stable" and canary.kills == 0
        # the postmortem names the regressing metric and slow traces
        pms = [p for p in flight.postmortems()
               if p["reason"] == "rollout_rejected"]
        assert pms, "rollout_rejected postmortem missing"
        meta = pms[-1]["meta"]
        assert meta["metric"] == "error_ratio"
        assert meta["source"] == src
        assert "t-slow" in meta["slow_traces"]
        # rejected identity is remembered: no re-canary next poll
        clock.advance(2.0)
        rc.tick()
        assert len(wire.swaps) == 1
    finally:
        flight.disable_flight()


def test_latency_regression_is_named(tmp_path):
    sup, reps, _ = _fleet(3)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock, max_p99_ratio=1.5,
                     p99_floor_ms=10.0)
    _bless_dir(tmp_path)
    rc.tick()
    canary_name = wire.swaps[0][0]
    _healthy_stats(wire, [r.name for r in reps], p99=0.020)
    wire.ts[canary_name]["p99"] = 0.200      # 10x the incumbents
    clock.advance(10.1)
    rc.tick()
    assert rc.last_verdict["metric"] == "latency_p99"
    assert rc.last_verdict["details"]["canary_p99_ms"] == 200.0


def test_probe_set_rejects_scrambled_model_immediately(tmp_path):
    sup, reps, _ = _fleet(3)
    wire = FakeWire()
    # a scrambled model answers the wrong class for every probe
    wire.predict_fn = lambda replica, body: [0.9, 0.1]
    clock = FakeClock()
    probes = [(np.zeros((2,), "float32"), 1)] * 4
    rc = _controller(tmp_path, sup, wire, clock, probe_set=probes,
                     probe_min_accuracy=0.75)
    _bless_dir(tmp_path)
    rc.tick()
    # rejected inside the SAME tick — no observation window burned
    assert rc.state == "idle"
    assert rc.last_verdict["decision"] == "rejected"
    assert rc.last_verdict["metric"] == "probe_accuracy"
    assert rc.last_verdict["details"]["probe_accuracy"] == 0.0
    assert wire.rollbacks == [wire.swaps[0][0]]


def test_canary_crash_mid_observation_aborts_without_rollback(tmp_path):
    sup, reps, _ = _fleet(3)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock)
    _bless_dir(tmp_path)
    rc.tick()
    canary = next(r for r in reps if r.name == wire.swaps[0][0])
    # supervisor relaunched it (generation bump): the fresh incarnation
    # loaded the INCUMBENT spec, so there is nothing to roll back
    canary.generation += 1
    clock.advance(1.0)
    rc.tick()
    assert rc.last_verdict["metric"] == "canary_crashed"
    assert wire.rollbacks == []
    assert rc.state == "idle"


def test_promote_swap_failure_reverts_already_swapped(tmp_path):
    sup, reps, spec = _fleet(3)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock)
    _bless_dir(tmp_path)
    rc.tick()
    canary_name = wire.swaps[0][0]
    _healthy_stats(wire, [r.name for r in reps])
    remaining = [r.name for r in reps if r.name != canary_name]
    wire.fail_swap_on = {remaining[-1]}      # second fan-out target fails
    clock.advance(10.1)
    rc.tick()
    assert rc.last_verdict["decision"] == "rejected"
    assert rc.last_verdict["metric"] == "promote_swap_failed"
    # the fleet reverted: the successfully-swapped target AND the canary
    assert set(wire.rollbacks) == {remaining[0], canary_name}
    assert spec.models == [("m", "/old/src")]
    assert rc.current_source == "/old/src"


def test_failed_rollback_kills_canary_so_supervisor_relaunches(tmp_path):
    sup, reps, _ = _fleet(3)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock)
    _bless_dir(tmp_path)
    rc.tick()
    canary = next(r for r in reps if r.name == wire.swaps[0][0])
    wire.fail_rollback_on = {canary.name}
    _healthy_stats(wire, [r.name for r in reps])
    wire.slo[canary.name]["objectives"][0]["ratio"] = 0.5
    clock.advance(10.1)
    rc.tick()
    # rollback refused -> the known-bad canary must not stay serving
    assert canary.kills == 1
    assert rc.last_verdict["rolled_back"] is False


# ------------------------------------------------------ admin-race guard
def test_manual_swap_racing_rollout_loses_loudly(tmp_path):
    """Satellite: an admin swap racing an in-flight canary must get a
    409 naming the rollout — never interleave with the fan-out."""
    sup, reps, spec = _fleet(3)
    wire = FakeWire()
    clock = FakeClock()
    rc = _controller(tmp_path, sup, wire, clock)
    _bless_dir(tmp_path)
    rc.tick()
    assert rc.holds_admin()
    router = ResilientRouter(sup.healthy, transport=wire, hedge=False,
                             rng=random.Random(0))
    server = RouterServer(router, supervisor=sup, rollout=rc)
    try:
        swaps_before = len(wire.swaps)
        req = urllib.request.Request(
            f"{server.url}/v1/models/m/swap",
            data=json.dumps({"source": "/manual/src"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 409
        doc = json.loads(exc.value.read())
        assert doc["rollout"]["state"] == "canary"
        assert "rollout" in doc["error"]
        # the losing call did NOT reach any replica
        assert len(wire.swaps) == swaps_before
        assert spec.models == [("m", "/old/src")]
        # once the rollout settles, manual admin works again
        _healthy_stats(wire, [r.name for r in reps])
        clock.advance(10.1)
        rc.tick()
        assert not rc.holds_admin()
        r = urllib.request.urlopen(req, timeout=10)
        assert r.status == 200 and json.loads(r.read())["ok"]
    finally:
        server.stop()


def test_fleet_rollback_rewrites_spec_like_swap(tmp_path):
    """Satellite: the PR-8 caveat is closed — a fleet-level rollback
    rewrites ReplicaSpec.models/lms to the version the replicas actually
    re-activated, so a restarted replica rejoins on the rolled-back
    version instead of the rejected one."""
    spec = ReplicaSpec([("m", "/rejected/src")], lms=[("m", "/rejected/src")])
    sup, reps, _ = _fleet(2)
    for r in reps:
        r.spec = spec

    def transport(replica, path, body, headers, timeout):
        return 200, {"Content-Type": "application/json"}, json.dumps(
            {"model": "m",
             "active": {"version": 1, "source": "/prev/good"}}).encode()

    router = ResilientRouter(sup.healthy, transport=transport, hedge=False,
                             rng=random.Random(0))
    server = RouterServer(router, supervisor=sup)
    try:
        req = urllib.request.Request(
            f"{server.url}/v1/models/m/rollback", data=b"{}",
            headers={"Content-Type": "application/json"})
        r = urllib.request.urlopen(req, timeout=10)
        assert r.status == 200 and json.loads(r.read())["ok"]
        assert spec.models == [("m", "/prev/good")]
        assert spec.lms == [("m", "/prev/good")]
    finally:
        server.stop()


# ------------------------------------------------- canary-aware routing
def test_router_bounds_canary_traffic_share():
    from tests.test_fleet import _ready_replicas, _ok_transport
    reps = _ready_replicas(3)
    reps[0].role = "canary"
    router = ResilientRouter(lambda: reps, transport=_ok_transport,
                             hedge=False, rng=random.Random(0),
                             canary_fraction=0.2)
    served = {r.name: 0 for r in reps}
    for _ in range(500):
        code, headers, _ = router.route_predict("m", b"{}", {})
        assert code == 200
        served[dict(headers)["X-Served-By"]] += 1
    share = served["r0"] / 500
    # ~20% target with p2c noise bounds; crucially NOT 1/3 (uniform)
    assert 0.10 < share < 0.30, served
    with pytest.raises(ValueError, match="canary_fraction"):
        ResilientRouter(lambda: reps, canary_fraction=0.8)


def test_readyz_surfaces_canary_state():
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import ModelServer
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    registry = ModelRegistry()
    registry.deploy("m", MultiLayerNetwork(conf).init(), buckets=(1, 8))
    server = ModelServer(registry, port=0)
    try:
        doc = json.loads(urllib.request.urlopen(
            f"{server.url}/readyz", timeout=10).read())
        assert doc["role"] == "stable" and doc["rollout_generation"] == 0
        req = urllib.request.Request(
            f"{server.url}/v1/rollout/role",
            data=json.dumps({"role": "canary",
                             "rollout_generation": 7}).encode(),
            headers={"Content-Type": "application/json"})
        assert urllib.request.urlopen(req, timeout=10).status == 200
        doc = json.loads(urllib.request.urlopen(
            f"{server.url}/readyz", timeout=10).read())
        assert doc["role"] == "canary" and doc["rollout_generation"] == 7
        bad = urllib.request.Request(
            f"{server.url}/v1/rollout/role",
            data=json.dumps({"role": "purple"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=10)
        assert exc.value.code == 400
    finally:
        server.stop()


# ----------------------------------------------------------- autoscaling
def _auto_supervisor(n=2, maximum=4, clock=None, **cfg_kw):
    clock = clock or FakeClock()
    reps = []

    def factory(i):
        r = FakeReplica(f"a{i}")
        reps.append(r)
        return r

    cfg_kw.setdefault("capacity_per_replica", 4)
    cfg_kw.setdefault("up_after_ticks", 2)
    cfg_kw.setdefault("down_after_ticks", 3)
    cfg_kw.setdefault("cooldown_s", 5.0)
    cfg = AutoscaleConfig(min_replicas=n, max_replicas=maximum, **cfg_kw)
    sup = ReplicaSupervisor(
        factory, n, time_fn=clock, sleep_fn=lambda s: None,
        rng=random.Random(0), probe_interval_s=1.0,
        spawn_fn=lambda fn, name: (fn(), None)[1],
        probe_fn=lambda r, timeout: r.probe_ok and r.alive(),
        autoscale=cfg)
    for r in sup.replicas:
        r.launch()
    return sup, reps, clock


def test_autoscale_scales_up_on_sustained_high_utilization():
    sup, reps, clock = _auto_supervisor(n=2, maximum=4)
    sup.tick()
    assert len(sup.replicas) == 2
    for r in reps:
        r.inflight_add(4)                  # 8/8 = 1.0 utilization
    clock.advance(1.0)
    sup.tick()                             # 1 tick above: not yet
    assert len(sup.replicas) == 2
    clock.advance(1.0)
    sup.tick()                             # 2nd consecutive tick: scale up
    assert len(sup.replicas) == 3
    new = sup.replicas[-1]
    assert new.name == "a2" and new.launches == 1
    clock.advance(1.0)
    sup.tick()
    assert new.state == "ready"
    assert monitor.REGISTRY.collect(
        "serving_autoscale_events_total").value(direction="up") >= 1
    # cooldown: still saturated but no second action inside cooldown_s
    clock.advance(1.0)
    sup.tick()
    clock.advance(1.0)
    sup.tick()
    assert len(sup.replicas) == 3
    # past cooldown it may grow again, but never beyond max_replicas
    for _ in range(10):
        clock.advance(2.0)
        sup.tick()
    assert len(sup.replicas) <= 4


def test_autoscale_scale_down_drains_never_kills():
    sup, reps, clock = _auto_supervisor(n=2, maximum=4)
    sup.tick()
    for r in reps:
        r.inflight_add(4)
    for _ in range(2):
        clock.advance(1.0)
        sup.tick()                         # scale up to 3
    assert len(sup.replicas) == 3
    victim = sup.replicas[-1]
    clock.advance(1.0)
    sup.tick()
    assert victim.state == "ready"
    for r in reps:
        r.inflight_add(-r.inflight())      # traffic stops: util 0
    clock.advance(6.0)                     # past cooldown
    for _ in range(3):                     # down_after_ticks
        clock.advance(1.0)
        sup.tick()
    # the victim DRAINED: begin_drain -> readyz confirmed -> graceful
    # stop; no kill, roster pruned back to the floor
    assert victim.draining is True
    assert victim.stops == 1 and victim.kills == 0
    assert victim.scaledown["readyz_confirmed"] is True
    assert victim.scaledown["forced_kill"] is False
    clock.advance(1.0)
    sup.tick()                             # prune the stopped victim
    assert len(sup.replicas) == 2
    assert victim not in sup.replicas
    # never below the floor, no matter how idle
    for _ in range(10):
        clock.advance(2.0)
        sup.tick()
    assert len(sup.replicas) == 2


def test_autoscale_never_drains_a_canary():
    sup, reps, clock = _auto_supervisor(n=2, maximum=4,
                                        down_after_ticks=1)
    sup.tick()
    # idle fleet, but the youngest ready replica is a canary under
    # rollout evaluation — it must never be the scale-down victim
    reps[-1].role = "canary"
    clock.advance(6.0)
    for _ in range(3):
        clock.advance(1.0)
        sup.tick()
    assert reps[-1].state == "ready"       # canary untouched
    # min_replicas=2 with one canary: the other replica is also safe
    assert all(r.state == "ready" for r in reps)


def test_autoscale_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscaleConfig(min_replicas=3, max_replicas=2,
                        capacity_per_replica=4)
    with pytest.raises(ValueError, match="watermark"):
        AutoscaleConfig(min_replicas=1, max_replicas=2,
                        capacity_per_replica=4,
                        low_watermark=0.9, high_watermark=0.8)
    with pytest.raises(ValueError, match="capacity"):
        AutoscaleConfig(min_replicas=1, max_replicas=2,
                        capacity_per_replica=0)
    # supervisor floor must sit inside the autoscale band
    with pytest.raises(ValueError, match="autoscale"):
        ReplicaSupervisor(
            lambda i: FakeReplica(f"v{i}"), 5,
            autoscale=AutoscaleConfig(min_replicas=1, max_replicas=3,
                                      capacity_per_replica=4))


# ------------------------------------------------- rollout drill (slow)
@pytest.mark.slow
def test_rollout_drill_end_to_end(tmp_path):
    """The acceptance run: train -> blessed checkpoint -> canary ->
    promote under live load with zero 5xx, then a poisoned checkpoint
    whose canary auto-rolls back with a postmortem naming the regressing
    metric, then an autoscaling ramp that scales up and drains down —
    all asserted by tools/rollout_drill.py itself (exit 0 == green)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = tmp_path / "ROLLOUT.json"
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "rollout_drill.py"),
         "--out", str(out)],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, timeout=580)
    assert proc.returncode == 0, \
        f"rollout drill failed:\n{proc.stdout[-4000:]}\n" \
        f"{proc.stderr[-2000:]}"
    report = json.loads(out.read_text())
    assert report["ok"] and not report["failures"]
    assert report["promote"]["server_5xx"] == 0
    assert report["rollback"]["postmortem_metric"] == "probe_accuracy"
    assert report["autoscale"]["peak_replicas"] > \
        report["autoscale"]["initial_replicas"]
    assert report["autoscale"]["forced_kills"] == 0
