"""Speculative decoding: draft-verify generation with provably
unchanged outputs.

The contract under test, layer by layer:

- the ``@spec[:draft=...,k=...]`` source-suffix grammar parses and maps
  onto DecodeConfig (`parse_variant` / `apply_variant`);
- the fused verify program is BITWISE the sequential decode program: one
  k-drafted `_verify_fn` call produces, position for position, the exact
  logits of k+1 single `step()` calls on an identical engine — across a
  KV page boundary (the property the greedy-parity guarantee rests on);
- greedy speculative streams are token-for-token equal to their
  non-speculative twin over 24+ steps, with the prefix cache on AND off;
- the temperature path is true rejection sampling: p==q always accepts,
  a zero-probability proposal deterministically rejects and resamples
  from the residual max(p-q, 0);
- a draft that disagrees with the target trips the rolling
  acceptance-rate floor (per-stream fallback counter, stream still
  completes, output still exact);
- a vocab-mismatched draft is rejected loudly at build time;
- speculative traffic never compiles on the request path: compiles ==
  warmups for the target AND its ``<name>.draft`` ledger labels.
"""

import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.serving.decode import (
    DecodeConfig, DecodeEngine, ServedLM, apply_variant,
)
from deeplearning4j_tpu.serving.quantize import (
    is_spec_variant, parse_variant,
)
from deeplearning4j_tpu.serving.registry import (
    ModelLoadError, load_servable,
)

ZOO_SRC = ("zoo:TransformerLM?vocab_size=48&n_layers=1&n_embd=32"
           "&n_heads=4&seq_length=32")
#: same arch, different init: a draft that legitimately serves the same
#: vocab but almost never matches the target's argmax
DRAFT_SRC = ZOO_SRC + "&seed=99"


def _tokens(req, timeout=60.0):
    """Drain one library GenerateRequest; returns (tokens, done info)."""
    toks = []
    deadline = time.monotonic() + timeout
    while True:
        ev = req.events.get(timeout=max(0.1, deadline - time.monotonic()))
        if ev[0] == "token":
            toks.append(int(ev[1]))
        elif ev[0] == "done":
            return toks, ev[1]
        else:
            raise ev[1]


# ------------------------------------------------------- variant grammar
def test_spec_variant_grammar_splits_at_first_spec():
    assert parse_variant("zoo:X?a=1@spec") == ("zoo:X?a=1", "spec")
    # the draft value may carry its own @int8 — the split is at the
    # FIRST @spec occurrence, not the last @
    src, variant = parse_variant("zoo:X@spec:draft=zoo:Y@int8,k=4")
    assert src == "zoo:X"
    assert variant == "spec:draft=zoo:Y@int8,k=4"
    assert is_spec_variant(variant)
    # plain quant splits stay at the last @
    assert parse_variant("zoo:X@int8") == ("zoo:X", "int8")
    assert not is_spec_variant("int8")
    assert parse_variant("zoo:X") == ("zoo:X", None)


def test_apply_variant_spec_options():
    cfg = DecodeConfig(slots=2, page_size=8)
    on = apply_variant(cfg, "spec")
    assert on.spec_draft == "int8"          # self-draft default
    assert on.spec_k == cfg.spec_k
    full = apply_variant(
        cfg, "spec:draft=bf16,k=2,floor=0.6,window=3,pool_pages=9")
    assert full.spec_draft == "bf16"
    assert full.spec_k == 2
    assert full.spec_accept_floor == 0.6
    assert full.spec_window == 3
    assert full.spec_draft_pool_pages == 9
    # the draft value keeps its own query string / quant suffix intact
    nested = apply_variant(cfg, f"spec:draft={DRAFT_SRC}")
    assert nested.spec_draft == DRAFT_SRC
    assert apply_variant(cfg, "int8").quantize == "int8"
    assert apply_variant(cfg, None) is cfg
    with pytest.raises(ValueError, match="key=value"):
        apply_variant(cfg, "spec:k4")
    with pytest.raises(ValueError, match="unknown @spec option"):
        apply_variant(cfg, "spec:bogus=1")
    with pytest.raises(ValueError, match="unknown servable variant"):
        apply_variant(cfg, "fp4")


# ------------------------------------------- verify-program bitwise oracle
def test_verify_program_bitwise_equals_sequential_steps():
    """One k-drafted verify call == k+1 sequential decode steps, logits
    compared bitwise per position, with the burst crossing a KV page
    boundary (prompt 6 + 4 rows over page_size 8)."""
    cfg = DecodeConfig(slots=2, page_size=8)
    a = DecodeEngine(load_servable(ZOO_SRC), cfg, name="vo-seq")
    b = DecodeEngine(load_servable(ZOO_SRC), cfg, name="vo-fused")
    try:
        prompt = np.array([1, 2, 3, 4, 5, 6], np.int32)
        k = 3
        sa = a.cache.admit(len(prompt))
        sb = b.cache.admit(len(prompt))
        t0a, _ = a.prefill(sa, prompt, 0.0, 0)
        t0b, _ = b.prefill(sb, prompt, 0.0, 0)
        assert t0a == t0b
        seq_logits, toks = [], [int(t0a)]
        for _ in range(k + 1):
            tk, act, lg = a.step()
            assert act[sa]
            seq_logits.append(lg[sa].copy())
            toks.append(int(tk[sa]))
        assert b.cache.ensure_capacity(sb, k + 1)     # 6 -> 10 rows: the
        assert (b.cache.page_table[sb, :2] > 0).all()  # burst spans 2 pages
        drafted = np.zeros((cfg.slots, k), np.int32)
        drafted[sb] = toks[1:k + 1]
        act = np.zeros((cfg.slots,), bool)
        act[sb] = True
        _, _, vlog = jax.jit(b._verify_fn)(
            b._params, b._kpool, b._vpool,
            np.asarray(b.cache.page_table),
            np.asarray(b.cache.seq_lens),
            b._last_tokens.copy(), drafted, act)
        vlog = np.asarray(vlog, np.float32)
        for i in range(k + 1):
            assert np.array_equal(vlog[sb, i], seq_logits[i]), \
                f"verify position {i} is not bitwise the {i + 1}-th step"
    finally:
        a.close()
        b.close()


# --------------------------------------------------- greedy spec parity
@pytest.fixture(scope="module")
def spec_pair():
    cfg = DecodeConfig(slots=2, page_size=8)
    plain = ServedLM("spec-plain", load_servable(ZOO_SRC), ZOO_SRC,
                     decode=cfg)
    spec = ServedLM("spec-on", load_servable(ZOO_SRC), ZOO_SRC,
                    decode=apply_variant(cfg, "spec:draft=int8,k=4"))
    yield plain, spec
    plain.shutdown(drain=False, timeout=5)
    spec.shutdown(drain=False, timeout=5)


def test_greedy_spec_parity_cache_on(spec_pair):
    """Greedy speculative == greedy plain, token for token, 24+ steps,
    prefix cache live (the second pass of each prompt admits hot)."""
    plain, spec = spec_pair
    eng = spec.scheduler.admitting_engine()
    assert eng.spec_enabled and eng.describe()["spec"]["k"] == 4
    prompts = [[1, 2, 3], [7, 8, 9, 10], [5] * 6, [1, 2, 3]]
    for prompt in prompts:
        pt, _ = _tokens(plain.generate(prompt, max_new_tokens=26))
        st, info = _tokens(spec.generate(prompt, max_new_tokens=26))
        assert len(st) >= 24
        assert pt == st, "speculation changed a greedy stream"
        assert info["spec_rounds"] > 0 and info["spec_proposed"] > 0
        assert 0 <= info["spec_accepted"] <= info["spec_proposed"]


def test_greedy_spec_parity_cache_off():
    cfg = DecodeConfig(slots=2, page_size=8, prefix_cache=False)
    plain = ServedLM("spec-plain-nc", load_servable(ZOO_SRC), ZOO_SRC,
                     decode=cfg)
    spec = ServedLM("spec-on-nc", load_servable(ZOO_SRC), ZOO_SRC,
                    decode=apply_variant(cfg, "spec:draft=int8,k=4"))
    try:
        for prompt in ([4, 5, 6], [11] * 5):
            pt, _ = _tokens(plain.generate(prompt, max_new_tokens=26))
            st, info = _tokens(spec.generate(prompt, max_new_tokens=26))
            assert len(st) >= 24 and pt == st
            assert info["spec_proposed"] > 0
    finally:
        plain.shutdown(drain=False, timeout=5)
        spec.shutdown(drain=False, timeout=5)


def test_temperature_spec_stream_completes(spec_pair):
    """The sampled path runs end to end through rejection sampling and
    still reports the speculative counters."""
    _, spec = spec_pair
    toks, info = _tokens(spec.generate([2, 4, 6], max_new_tokens=20,
                                       temperature=0.9, top_k=8))
    assert len(toks) == 20
    assert all(0 <= t < 48 for t in toks)
    assert info["spec_rounds"] > 0
    assert 0 <= info["spec_accepted"] <= info["spec_proposed"]


# ------------------------------------------------- rejection sampler math
@pytest.fixture(scope="module")
def bare_engine():
    eng = DecodeEngine(load_servable(ZOO_SRC),
                       DecodeConfig(slots=1, page_size=8), name="rj")
    yield eng
    eng.close()


def test_greedy_accept_is_argmax_prefix_match(bare_engine):
    v = 8
    vlog = np.full((4, v), -5.0, np.float32)
    vlog[0, 4] = 5.0
    vlog[1, 7] = 5.0
    vlog[2, 2] = 5.0          # target argmax 2 disagrees with draft's 1
    vlog[3, 6] = 5.0
    a, extra = bare_engine._spec_accept(
        np.array([4, 7, 1]), vlog, vlog[:3], 0.0, 0)
    assert (a, extra) == (2, 2)   # prefix accepted, target's own argmax
    a, extra = bare_engine._spec_accept(
        np.array([4, 7, 2]), vlog, vlog[:3], 0.0, 0)
    assert (a, extra) == (3, 6)   # full acceptance + bonus token


def test_rejection_sampling_p_equals_q_always_accepts(bare_engine):
    rs = np.random.RandomState(5)
    lg = rs.randn(4, 16).astype(np.float32)
    for _ in range(8):            # accept prob is exactly 1, any rng draw
        a, extra = bare_engine._spec_accept(
            np.array([3, 9, 14]), lg, lg[:3], 0.7, 0)
        assert a == 3 and 0 <= extra < 16


def test_rejection_resamples_residual_deterministically(bare_engine):
    """q one-hot at 1, p one-hot at 2: p(d)=0 forces rejection at i=0
    (accept prob 0 beats any rng draw) and the residual max(p-q, 0) is
    one-hot at the target's token."""
    v = 8
    qlog = np.full((1, v), -1e9, np.float32)
    qlog[0, 1] = 0.0
    vlog = np.full((2, v), -1e9, np.float32)
    vlog[0, 2] = 0.0
    a, extra = bare_engine._spec_accept(
        np.array([1]), vlog, qlog, 1.0, 0)
    assert (a, extra) == (0, 2)


def test_spec_dist_matches_sampler_topk_clip(bare_engine):
    """The host-side q/p recomputation applies the SAME top-k clip as
    the in-graph sampler: mass lands only on the k highest logits."""
    lg = np.arange(16, dtype=np.float32)
    p = bare_engine._spec_dist(lg, 1.0, 4)
    assert np.all(p[:-4] == 0.0) and abs(p.sum() - 1.0) < 1e-12
    assert np.argmax(p) == 15


# -------------------------------------------- acceptance-floor fallback
def test_low_acceptance_trips_floor_and_output_is_still_exact(spec_pair):
    """A same-vocab but differently-initialized draft almost never
    matches the target's argmax: the rolling window trips the floor,
    the stream falls back to plain decode, and the greedy output is
    STILL token-for-token the non-speculative stream."""
    plain, _ = spec_pair
    cfg = DecodeConfig(slots=2, page_size=8)
    bad = ServedLM(
        "spec-fb", load_servable(ZOO_SRC), ZOO_SRC,
        decode=apply_variant(
            cfg, f"spec:draft={DRAFT_SRC},k=4,floor=0.9,window=2"))
    try:
        prompt = [3, 1, 4, 1, 5]
        pt, _ = _tokens(plain.generate(prompt, max_new_tokens=26))
        st, info = _tokens(bad.generate(prompt, max_new_tokens=26))
        assert pt == st, "fallback path changed the stream"
        assert len(st) >= 24
        fb = monitor.counter(
            "serving_decode_spec_fallbacks_total", "x",
            labels=("model", "reason")).value(
                model="spec-fb", reason="acceptance_floor")
        assert fb >= 1
    finally:
        bad.shutdown(drain=False, timeout=5)


# --------------------------------------------------- loud build failures
def test_vocab_mismatched_draft_rejected_at_build():
    mismatched = ZOO_SRC.replace("vocab_size=48", "vocab_size=32")
    cfg = apply_variant(DecodeConfig(slots=1, page_size=8),
                        f"spec:draft={mismatched}")
    with pytest.raises(ModelLoadError, match="vocab"):
        DecodeEngine(load_servable(ZOO_SRC), cfg, name="vmm")


def test_spec_k_must_be_positive():
    cfg = apply_variant(DecodeConfig(slots=1, page_size=8), "spec:k=0")
    with pytest.raises(ModelLoadError, match="spec_k"):
        DecodeEngine(load_servable(ZOO_SRC), cfg, name="k0")


# ----------------------------------------------------- compile ledger
def test_spec_traffic_never_compiles_on_request_path(spec_pair):
    """After real speculative traffic (the parity/temperature tests
    above), compiles == warmups for the target AND its draft ledger
    labels: the draft_{k} and verify_{k+1} programs were AOT-warmed."""
    def fam_sum(family, model):
        total = 0.0
        for line in monitor.prometheus_text().splitlines():
            if line.startswith(family + "{") and f'model="{model}"' in line:
                total += float(line.rsplit(" ", 1)[1])
        return total

    for model in ("spec-on", "spec-on.draft"):
        csum = fam_sum("serving_decode_compiles_total", model)
        wsum = fam_sum("serving_decode_warmup_runs_total", model)
        assert csum == wsum and csum > 0, (model, csum, wsum)
