"""TPU Mosaic-lowering regression tests — no hardware required.

The Pallas kernels run under interpret=True everywhere on CPU, so a
BlockSpec/tiling bug that only Mosaic's TPU lowering rejects never
surfaces in the normal suite — exactly what happened at first hardware
contact in the round-5 sweep (the attention micro died with the
grid_blockspec error while the tunnel was healthy; fixed by carrying the
rank-2 operands as rank-3 with singleton middle dims).

`jax.export.export(..., platforms=['tpu'])` runs the REAL Mosaic
lowering pass (it ships in jaxlib, no TPU needed), so these tests retire
that whole failure class at CI time: if a kernel change breaks TPU
tiling rules, the quick gate catches it before a hardware window is
spent discovering it. Each flash test also asserts the exported module
contains a `tpu_custom_call` — proof the Pallas kernel (not the
interpret-mode emulation) is what was lowered.

Reference anchor: the cuDNN-helper seam these kernels replace
(deeplearning4j-cuda/.../CudnnConvolutionHelper.java) has no CPU-side
validation either — this is the TPU-native improvement on that story.
"""
import jax
import jax.numpy as jnp
import pytest


def _export_tpu(fn, *args, expect_pallas: bool = True):
    exported = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    if expect_pallas:
        mlir = exported.mlir_module()
        assert "tpu_custom_call" in mlir, (
            "exported module contains no Mosaic kernel — the Pallas path "
            "was not taken (interpret-mode emulation lowered instead)")
    return exported


class TestFlashKernelLowering:
    def test_forward_causal_bf16(self):
        from deeplearning4j_tpu.ops.flash_attention import flash_attention
        q = jnp.zeros((2, 512, 4, 64), jnp.bfloat16)
        _export_tpu(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=False), q, q, q)

    def test_forward_masked_with_padding(self):
        # t=300 is not a multiple of the 128 block: exercises the
        # internal pad path (padded keys mask-excluded) under Mosaic
        from deeplearning4j_tpu.ops.flash_attention import flash_attention
        q = jnp.zeros((2, 300, 4, 64), jnp.bfloat16)
        m = jnp.ones((2, 300), jnp.bfloat16)
        _export_tpu(lambda q, k, v, m: flash_attention(
            q, k, v, mask=m, interpret=False), q, q, q, m)

    def test_backward_kernels_with_lse_cotangent(self):
        # grad through out AND lse covers the dq kernel, the dk/dv
        # kernel, and the lse-cotangent fold into delta
        from deeplearning4j_tpu.ops.flash_attention import flash_attention

        def loss(q, k, v):
            o, lse = flash_attention(q, k, v, causal=True,
                                     interpret=False, return_lse=True)
            return jnp.sum(o.astype(jnp.float32)) + jnp.sum(lse)

        q = jnp.zeros((2, 512, 4, 64), jnp.bfloat16)
        _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)

    def test_cross_attention_shapes(self):
        from deeplearning4j_tpu.ops.flash_attention import flash_attention
        q = jnp.zeros((2, 256, 4, 64), jnp.bfloat16)
        k = jnp.zeros((2, 1024, 4, 64), jnp.bfloat16)

        def loss(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, interpret=False).astype(jnp.float32))

        _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, k)


class TestRingFlashLowering:
    def test_ring_flash_over_seq_mesh(self):
        import functools
        from jax.sharding import Mesh, PartitionSpec as P
        from deeplearning4j_tpu.parallel.mesh import compat_shard_map
        from deeplearning4j_tpu.parallel.ring import (
            ring_flash_self_attention, SEQ_AXIS)

        mesh = Mesh(jax.devices()[:4], (SEQ_AXIS,))
        # interpret=False forced: the default resolves against the CPU
        # backend at trace time and would export the emulation instead
        fn = compat_shard_map(
            functools.partial(ring_flash_self_attention, causal=True,
                              interpret=False),
            mesh,
            in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS),
                      P(None, SEQ_AXIS)),
            out_specs=P(None, SEQ_AXIS))
        q = jnp.zeros((2, 512, 4, 64), jnp.bfloat16)
        _export_tpu(fn, q, q, q)


class TestFlagshipLowering:
    def test_graft_entry_forward_lowers_for_tpu(self):
        # the driver compile-checks entry() on whatever chip it has;
        # this pins the TPU lowering of the same program at CI time.
        # No Pallas expected here — entry() is the plain-XLA flagship.
        import __graft_entry__ as ge
        fn, args = ge.entry()
        _export_tpu(fn, *args, expect_pallas=False)

    @pytest.mark.parametrize("s2d", [False, True])
    def test_resnet_train_step_lowers_for_tpu(self, s2d):
        # the bench's headline program at the REAL hardware spatial shape
        # (224x224 bf16) — a regression in the stem/device-norm/zoo that
        # only breaks TPU lowering must fail here, not in a tunnel window
        import dataclasses

        import optax

        from deeplearning4j_tpu.models import ResNet50
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        model = ResNet50(num_classes=1000, input_shape=(224, 224, 3),
                         space_to_depth_stem=s2d)
        conf = dataclasses.replace(model.conf(),
                                   compute_dtype="bfloat16")
        net = ComputationGraph(conf).init()
        tx = net._tx
        x = jnp.zeros((8, 224, 224, 3), jnp.bfloat16)
        y = jnp.zeros((8, 1000), jnp.bfloat16)

        def step(params, opt_state, state, x, y, rng):
            def loss_fn(p):
                loss, (new_state, _) = net._score_fn(
                    p, state, (x,), (y,), None, None, True, rng)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_opt,
                    new_state, loss)

        _export_tpu(step, net.params, net.opt_state, net.state, x, y,
                    jax.random.PRNGKey(0), expect_pallas=False)
