"""Full-batch solver family (DL4J optimize/solvers/ parity:
BackTrackLineSearch.java:64, ConjugateGradient.java:40, LBFGS.java:39)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.train import (
    BackTrackLineSearch, ConjugateGradient, LBFGS, LineGradientDescent,
)


def _blob_data(n=200, d=6, k=3, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    X = np.concatenate([centers[i] + rs.randn(n // k, d)
                        for i in range(k)]).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    return X, Y


def _logreg(seed=0):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(1e-2)).list()
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(1e-2)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def test_backtrack_line_search_sufficient_decrease():
    """On f(x) = ||x||^2 the Armijo condition must hold for the accepted
    step, starting from a point where step=1 along -g overshoots."""
    import jax

    @jax.jit
    def f(x):
        return jnp.sum(x * x)

    vg = jax.jit(jax.value_and_grad(f))
    x0 = jnp.full((5,), 3.0)
    f0, g0 = vg(x0)
    ls = BackTrackLineSearch(vg, max_iterations=10)
    step, x1, f1 = ls.optimize(x0, f0, g0, -g0)
    assert step > 0
    slope = float(jnp.vdot(g0, -g0))
    assert f1 <= float(f0) + ls.ALF * step * slope
    assert f1 < float(f0)


@pytest.mark.parametrize("solver_cls",
                         [LineGradientDescent, ConjugateGradient, LBFGS])
def test_solvers_converge_logreg(solver_cls):
    X, Y = _blob_data()
    net = _logreg()
    before = net.score((__import__(
        "deeplearning4j_tpu.data.dataset", fromlist=["DataSet"])
        .DataSet(X, Y)))
    res = solver_cls(max_iterations=60).optimize(net, (X, Y))
    assert res.final_score < 0.3 * before, res.scores[:5] + res.scores[-3:]
    acc = net.evaluate((X, Y)).accuracy()
    assert acc > 0.93, acc
    # monotone non-increasing scores (line search guarantees descent)
    diffs = np.diff(res.scores)
    assert np.all(diffs <= 1e-6), res.scores


def test_lbfgs_beats_gradient_descent_iterations():
    """Curvature exploitation: on the same budget L-BFGS must reach a
    lower loss than steepest descent (the reason the family exists)."""
    X, Y = _blob_data(seed=3)
    net_gd = _mlp(seed=5)
    net_lb = _mlp(seed=5)
    r_gd = LineGradientDescent(max_iterations=25).optimize(net_gd, (X, Y))
    r_lb = LBFGS(max_iterations=25).optimize(net_lb, (X, Y))
    assert r_lb.final_score < r_gd.final_score, \
        (r_lb.final_score, r_gd.final_score)


def test_cg_works_on_graph():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    X, Y = _blob_data()
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(1)
                      .updater(Sgd(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(6)))
    g.add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
    g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"), "d")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    res = ConjugateGradient(max_iterations=40).optimize(net, (X, Y))
    assert res.final_score < res.scores[0] * 0.5
    assert net.evaluate(__import__(
        "deeplearning4j_tpu.data.dataset", fromlist=["DataSet"])
        .DataSet(X, Y)).accuracy() > 0.9
