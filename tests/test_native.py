"""Native C++ host kernels (the analog of ND4J's out-of-tree native ops:
thresholdEncode compression — EncodingHandler.java:136-178 — and the
AggregateSkipGram HogWild aggregates — SkipGram.java:224-272)."""
import numpy as np
import pytest

from deeplearning4j_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")


def test_codec_round_trip_and_top_k_selection():
    rs = np.random.RandomState(0)
    g = rs.randn(2000).astype("float32") * 0.01
    big_idx = rs.choice(2000, 40, replace=False)
    g[big_idx] = np.sign(g[big_idx]) * (0.5 + rs.rand(40))
    idx, vals, residual = native.threshold_encode(g, 0.1, cap=100)
    assert len(idx) == 40
    assert set(idx.tolist()) == set(big_idx.tolist())
    np.testing.assert_allclose(vals, g[idx], atol=0)
    dense = native.decode_accumulate(np.zeros(2000, "float32"), idx, vals)
    np.testing.assert_allclose(dense + residual, g, atol=1e-7)
    # cap enforcement keeps the LARGEST magnitudes
    idx2, vals2, _ = native.threshold_encode(g, 0.0, cap=10)
    assert len(idx2) == 10
    kept = np.sort(np.abs(vals2))
    top10 = np.sort(np.abs(g))[-10:]
    np.testing.assert_allclose(kept, top10, atol=0)


def test_codec_matches_jax_path():
    """Host codec and the compiled XLA encoder agree on selection, values,
    and residual (backend equivalence — the cuDNN-vs-builtin test pattern,
    SURVEY.md §4)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.parallel.encoding import threshold_encode_values
    rs = np.random.RandomState(1)
    g = rs.randn(512).astype("float32")
    j_idx, j_vals, j_res = threshold_encode_values(jnp.asarray(g), 0.8, 64)
    n_idx, n_vals, n_res = native.threshold_encode(g, 0.8, 64)
    j_valid = np.asarray(j_idx) >= 0
    assert set(np.asarray(j_idx)[j_valid].tolist()) == set(n_idx.tolist())
    np.testing.assert_allclose(np.asarray(j_res), n_res, atol=1e-7)


def test_encoding_handler_native_backend():
    from deeplearning4j_tpu.parallel.encoding import EncodingHandler
    h = EncodingHandler(threshold=0.1, boundary=0.5, backend="native")
    g = np.full(100, 0.06, "float32")
    idx, vals, thr = h.encode(g)          # below threshold: nothing sent
    assert len(idx) == 0
    idx, vals, thr = h.encode(g)          # residual pushes over
    assert len(idx) == 100
    np.testing.assert_allclose(vals, 0.12, atol=1e-6)


def test_hogwild_skipgram_learns_topic_structure():
    """The C++ HogWild trainer must learn the same co-occurrence structure
    as the device backend (Word2Vec backend='native')."""
    from deeplearning4j_tpu.embeddings import Word2Vec
    from deeplearning4j_tpu.text import CollectionSentenceIterator
    rs = np.random.RandomState(3)
    animals = ["cat", "dog", "pet", "fur", "tail"]
    vehicles = ["car", "bus", "road", "wheel", "engine"]
    sents = []
    for _ in range(400):
        pool = animals if rs.rand() < 0.5 else vehicles
        sents.append(" ".join(rs.choice(pool, 6)))
    w2v = Word2Vec(layer_size=32, window=3, min_count=2, negative=5,
                   epochs=25, backend="native", n_threads=2, seed=1)
    w2v.fit(CollectionSentenceIterator(sents))
    assert len(w2v.vocab) == 10
    assert np.isfinite(w2v.last_loss) and w2v.last_loss > 0
    same = w2v.similarity("cat", "dog")
    cross = w2v.similarity("cat", "car")
    assert same > cross, (same, cross)
    near = w2v.words_nearest("bus", 4)
    assert set(near).issubset(set(vehicles)), near


def test_native_backend_rejects_unsupported_modes():
    from deeplearning4j_tpu.embeddings import Word2Vec
    from deeplearning4j_tpu.text import CollectionSentenceIterator
    w2v = Word2Vec(layer_size=8, min_count=1, negative=0,
                   use_hierarchic_softmax=True, backend="native")
    with pytest.raises(ValueError, match="native"):
        w2v.fit(CollectionSentenceIterator(["a b c d"]))


def test_shared_gradients_two_process_uses_native_codec():
    """The rank/DCN trainer advertises the native codec when available."""
    from deeplearning4j_tpu.parallel.shared import SharedGradientsTrainer
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.transport import SocketTransport
    import socket
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(1e-2))
            .list().layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    with SocketTransport(rank=0, n_workers=1, base_port=port) as tr:
        t = SharedGradientsTrainer(net, n_workers=1, rank=0, transport=tr)
        assert t.handlers[0].backend == "native"


def test_ns_table_never_contains_out_of_vocab_ids():
    """Regression: float32 cumsum rounding used to leak id==V into the
    negative-sampling table, which the unchecked C++ kernel would index
    out of bounds (heap corruption)."""
    from deeplearning4j_tpu.embeddings import Word2Vec
    from deeplearning4j_tpu.text import CollectionSentenceIterator
    rs = np.random.RandomState(0)
    # Zipf-ish vocabulary large enough to trigger the rounding
    words = [f"w{i}" for i in range(1000)]
    freqs = (1.0 / (np.arange(1000) + 1)) ** 0.9
    sents = []
    for _ in range(300):
        ids = rs.choice(1000, 8, p=freqs / freqs.sum())
        sents.append(" ".join(words[i] for i in ids))
    w2v = Word2Vec(layer_size=8, min_count=1, negative=5, epochs=1,
                   backend="native", seed=0)
    w2v.build_vocab(CollectionSentenceIterator(sents))
    V = len(w2v.vocab)
    p = w2v.vocab.unigram_table()
    cum = np.cumsum(np.asarray(p, np.float64))
    cum /= cum[-1]
    table = np.minimum(
        np.searchsorted(cum, (np.arange(1_000_000) + 0.5) / 1_000_000),
        V - 1)
    assert table.max() < V and table.min() >= 0
    # and the full native fit survives (would corrupt/segfault before)
    w2v.fit(CollectionSentenceIterator(sents))
    assert np.all(np.isfinite(w2v.vectors))


# --------------------------------------------------------------------------
# INDArray op contract (src/ndarray_ops.cpp + native/ndarray.py): the host
# half of the surface the reference consumes from libnd4j (SURVEY.md §2.1 —
# gemm LSTMHelpers.java:212, im2col ConvolutionLayer.java:215, Transforms,
# reductions, broadcasts, random). Each test is a backend-equivalence
# check against the numpy oracle.

def test_ndarray_gemm_matches_numpy_all_transposes():
    from deeplearning4j_tpu.native.ndarray import HostNDArray
    rs = np.random.RandomState(0)
    A = rs.randn(37, 23).astype("float32")
    B = rs.randn(23, 41).astype("float32")
    ref = A @ B
    np.testing.assert_allclose(
        HostNDArray(A).mmul(HostNDArray(B)).numpy(), ref, atol=1e-4)
    np.testing.assert_allclose(
        HostNDArray(A.T.copy()).mmul(HostNDArray(B),
                                     transpose_a=True).numpy(),
        ref, atol=1e-4)
    np.testing.assert_allclose(
        HostNDArray(A).mmul(HostNDArray(B.T.copy()),
                            transpose_b=True).numpy(),
        ref, atol=1e-4)
    np.testing.assert_allclose(
        HostNDArray(A).mmul(HostNDArray(B), alpha=0.5).numpy(),
        0.5 * ref, atol=1e-4)


def test_ndarray_transforms_reductions_broadcasts():
    from deeplearning4j_tpu.native.ndarray import HostNDArray
    rs = np.random.RandomState(1)
    A = rs.randn(19, 31).astype("float32")
    a = HostNDArray(A)
    np.testing.assert_allclose(a.tanh().numpy(), np.tanh(A), atol=1e-6)
    np.testing.assert_allclose(a.sigmoid().numpy(),
                               1 / (1 + np.exp(-A)), atol=1e-6)
    np.testing.assert_allclose(a.relu().numpy(), np.maximum(A, 0),
                               atol=0)
    np.testing.assert_allclose((a + 1.5).numpy(), A + 1.5, atol=1e-6)
    np.testing.assert_allclose((a * a).numpy(), A * A, atol=1e-6)
    np.testing.assert_allclose(a.sum(axis=1).numpy(), A.sum(1), atol=1e-3)
    np.testing.assert_allclose(a.mean(axis=0).numpy(), A.mean(0),
                               atol=1e-4)
    np.testing.assert_allclose(a.max(axis=1).numpy(), A.max(1), atol=0)
    assert (a.argmax(axis=1) == A.argmax(1)).all()
    assert abs(a.norm2() - np.linalg.norm(A)) < 1e-2
    v = rs.randn(31).astype("float32")
    np.testing.assert_allclose((a + v).numpy(), A + v, atol=1e-6)
    np.testing.assert_allclose(a.broadcast_row("div", v).numpy(), A / v,
                               atol=1e-4)
    assert abs(float(a.sum()) - float(A.sum())) < 1e-2


def test_ndarray_im2col_col2im_adjoint_and_equivalence():
    from deeplearning4j_tpu.native import ndarray as nd
    rs = np.random.RandomState(2)
    img = rs.randn(3, 11, 9).astype("float32")
    cols = nd.im2col(img, 3, 3, 2, 2, 1, 1)
    # backend equivalence vs the numpy fallback
    lib, native._lib = native._lib, None
    native._build_failed = True
    try:
        cols_np = nd.im2col(img, 3, 3, 2, 2, 1, 1)
    finally:
        native._lib, native._build_failed = lib, False
    np.testing.assert_allclose(cols, cols_np, atol=0)
    # adjoint identity: <im2col(x), y> == <x, col2im(y)>
    y = rs.randn(*cols.shape).astype("float32")
    lhs = float((cols * y).sum())
    rhs = float((img * nd.col2im(y, 3, 11, 9, 3, 3, 2, 2, 1, 1)).sum())
    assert abs(lhs - rhs) < 1e-2


def test_ndarray_random_and_distance_kernels():
    from deeplearning4j_tpu.native import ndarray as nd
    r = nd.HostNDArray.randn(20000, seed=7)
    assert abs(float(r.mean())) < 0.05
    assert abs(float(np.std(r.numpy())) - 1.0) < 0.05
    u = nd.HostNDArray.rand(20000, seed=7, lo=-2.0, hi=2.0).numpy()
    assert u.min() >= -2.0 and u.max() <= 2.0
    assert abs(u.mean()) < 0.1
    rs = np.random.RandomState(3)
    X = rs.randn(64, 17).astype("float32")
    Q = rs.randn(9, 17).astype("float32")
    np.testing.assert_allclose(
        nd.pairwise_sqdist(X, Q),
        ((X[:, None, :] - Q[None]) ** 2).sum(-1), atol=1e-3)
    b = rs.randint(0, 256, (13, 28, 28)).astype(np.uint8)
    np.testing.assert_allclose(nd.scale_u8(b, 1 / 255.0),
                               b.astype("float32") / 255.0, atol=1e-6)


def test_ndarray_edge_semantics_match_across_backends():
    """Backend-divergence regressions (advisor r3): NaN relu, empty
    reductions, reflected scalar ops, axis validation, div-by-zero."""
    from deeplearning4j_tpu.native import ndarray as nd

    def both(fn):
        out_native = fn()
        lib, failed = native._lib, native._build_failed
        native._lib, native._build_failed = None, True
        try:
            out_numpy = fn()
        finally:
            native._lib, native._build_failed = lib, failed
        return out_native, out_numpy

    # relu(NaN) propagates NaN on both backends
    x = nd.HostNDArray(np.array([1.0, -2.0, np.nan], np.float32))
    a, b = both(lambda: nd.HostNDArray(
        np.array([1.0, -2.0, np.nan], np.float32)).relu().numpy())
    np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
    np.testing.assert_allclose(a[:2], [1.0, 0.0])
    assert np.isnan(a[2])

    # empty reductions: sum -> 0, mean/max -> NaN, both backends
    empty = lambda: nd.HostNDArray(np.empty((0,), np.float32))
    for name, want_nan in [("sum", False), ("mean", True), ("max", True)]:
        a, b = both(lambda n=name: getattr(empty(), n)())
        if want_nan:
            assert np.isnan(a) and np.isnan(b)
        else:
            assert a == 0.0 and b == 0.0

    # axis normalization and validation
    m = nd.HostNDArray(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(m.sum(axis=-1).numpy(),
                               m.sum(axis=1).numpy())
    with pytest.raises(ValueError):
        m.sum(axis=2)

    # reflected scalar ops
    np.testing.assert_allclose((10.0 - m).numpy(), 10.0 - m.numpy())
    np.testing.assert_allclose((6.0 / (m + 1.0)).numpy(),
                               6.0 / (m.numpy() + 1.0), rtol=1e-6)
    # scalar division by zero -> inf, not an exception
    assert np.isposinf((m + 1.0).__truediv__(0.0).numpy()).all()


def test_ndarray_argmax_empty_raises_and_rdiv_exact():
    from deeplearning4j_tpu.native import ndarray as nd
    with pytest.raises(ValueError):
        nd.HostNDArray(np.empty((0, 5), np.float32)).argmax(axis=0)
    # reflected division is exact elementwise division, not reciprocal*mul
    x = nd.HostNDArray(np.array([1e-40, 2.0], np.float32))
    out = (1e-5 / x).numpy()
    assert np.isfinite(out[0]) and out[0] == np.float32(1e-5) / np.float32(1e-40)


def test_native_csv_parser_matches_python_and_falls_back(tmp_path):
    """Strict C++ numeric-CSV fast path: identical values to the python
    reader, loud fallback (None) for anything non-numeric/ragged."""
    from deeplearning4j_tpu.data.records import (
        CSVRecordReader, parse_numeric_csv,
    )
    rs = np.random.RandomState(0)
    M = rs.randn(500, 8).astype("float32")
    p = tmp_path / "num.csv"
    with open(p, "w") as f:
        f.write("h1,h2,h3,h4,h5,h6,h7,h8\n")      # header skipped
        for row in M:
            f.write(",".join(f"{v:.6g}" for v in row) + "\n")
    mat = parse_numeric_csv(str(p), ",", skip_lines=1)
    if not native.available():
        assert mat is None
        return
    assert mat.shape == (500, 8)
    np.testing.assert_allclose(mat, M, rtol=1e-5)
    # records() keeps the python float64-list contract
    rows = list(CSVRecordReader(str(p), skip_lines=1).records())
    assert isinstance(rows[0], list)
    np.testing.assert_allclose(np.asarray(rows, np.float32), M, rtol=1e-5)
    # strict parser rejects what python float() would treat differently
    hexf = tmp_path / "hex.csv"
    hexf.write_text("1,0x10\n")
    assert parse_numeric_csv(str(hexf)) is None
    over = tmp_path / "over.csv"
    over.write_text("1e39,2\n")
    assert parse_numeric_csv(str(over)) is None

    # non-numeric and ragged files fall back (None from the fast path)
    bad = tmp_path / "bad.csv"
    bad.write_text("1,2,3\n4,abc,6\n")
    assert parse_numeric_csv(str(bad)) is None
    ragged = tmp_path / "ragged.csv"
    ragged.write_text("1,2,3\n4,5\n")
    assert parse_numeric_csv(str(ragged)) is None
    # python fallback still raises its usual error for non-numeric
    with pytest.raises(ValueError):
        list(CSVRecordReader(str(bad)).records())

    # and the full RecordReaderDataSetIterator flow on the fast path
    from deeplearning4j_tpu.data.records import RecordReaderDataSetIterator
    lab = tmp_path / "labeled.csv"
    with open(lab, "w") as f:
        for i in range(30):
            f.write(f"{i * 0.1:.3f},{i * 0.2:.3f},{i % 3}\n")
    it = RecordReaderDataSetIterator(CSVRecordReader(str(lab)),
                                     batch_size=10, label_index=2,
                                     num_classes=3)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].features.shape == (10, 2)
    assert batches[0].labels.shape == (10, 3)


# ------------------------------------------------- native batch tokenizer

class TestNativeTokenizer:
    def setup_method(self):
        from deeplearning4j_tpu import native
        if not native.available():
            pytest.skip("no native toolchain")

    def test_count_parity_with_python_tokenizer(self):
        from collections import Counter

        from deeplearning4j_tpu.text.native_tokenizer import (
            NativeCorpusEncoder,
        )
        from deeplearning4j_tpu.text.tokenization import (
            CommonPreprocessor, DefaultTokenizerFactory,
        )
        docs = [
            "The QUICK brown fox, jumped over 12 lazy dogs!",
            "Hello... world; (parens) [brackets] \"quotes\" 'single'",
            "a/b c|d e?f g!h i;j",
            "",
            "repeated repeated repeated words words",
        ]
        tf = DefaultTokenizerFactory(CommonPreprocessor())
        expected = Counter()
        for d in docs:
            expected.update(tf.tokenize(d))
        got = NativeCorpusEncoder().count_or_none(docs)
        assert got is not None
        assert got == dict(expected)

    def test_encode_parity_and_oov(self):
        from deeplearning4j_tpu.text.native_tokenizer import (
            NativeCorpusEncoder,
        )
        from deeplearning4j_tpu.text.tokenization import (
            CommonPreprocessor, DefaultTokenizerFactory,
        )
        docs = ["The cat sat, on the MAT!", "dog und cat 99", ""]
        word2id = {"the": 7, "cat": 3, "sat": 5, "on": 2, "mat": 11,
                   "dog": 13}
        enc = NativeCorpusEncoder()
        out = enc.encode_or_none(docs, word2id)
        assert out is not None and len(out) == 3
        tf = DefaultTokenizerFactory(CommonPreprocessor())
        for d, ids in zip(docs, out):
            exp = [word2id[t] for t in tf.tokenize(d) if t in word2id]
            assert list(ids) == exp
        # keep_oov marks unknowns as -1 ("und" and the stripped "99" -> "")
        out2 = enc.encode_or_none(docs, word2id, keep_oov=True)
        assert list(out2[1]) == [13, -1, 3]

    def test_non_ascii_falls_back(self):
        from deeplearning4j_tpu.text.native_tokenizer import (
            NativeCorpusEncoder,
        )
        assert NativeCorpusEncoder().encode_or_none(
            ["héllo wörld"], {"hello": 0}) is None

    def test_newline_in_doc_falls_back(self):
        from deeplearning4j_tpu.text.native_tokenizer import (
            NativeCorpusEncoder,
        )
        assert NativeCorpusEncoder().encode_or_none(
            ["two\nlines"], {"two": 0}) is None


def test_word2vec_native_vocab_matches_python_pass():
    """Word2Vec.build_vocab's C++ counting pass must produce the identical
    vocabulary (words, counts, frequency order) as the Python pass."""
    from deeplearning4j_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")
    from deeplearning4j_tpu.embeddings.word2vec import Word2Vec

    corpus = ["The king and the queen ruled.",
              "A dog and a cat; the dog barked!",
              "king queen king queen KING"] * 3
    w_native = Word2Vec(layer_size=8, min_count=2)
    w_native.build_vocab(corpus)
    assert w_native._native_counts(corpus) is not None  # fast path taken

    w_py = Word2Vec(layer_size=8, min_count=2)
    # force the Python pass by handing a generator (not list/tuple)
    w_py.build_vocab(iter(corpus))

    assert len(w_native.vocab) == len(w_py.vocab) > 0
    for i in range(len(w_py.vocab)):
        wa, wb = w_native.vocab.word_for(i), w_py.vocab.word_for(i)
        assert wa == wb
        assert w_native.vocab.count_of(wa) == w_py.vocab.count_of(wb)


def test_native_tokenizer_fs_gs_rs_us_separators():
    """Python str.split() splits on \\x1c-\\x1f; the native pass must
    agree (review finding: vocab divergence on FS/GS separators)."""
    from deeplearning4j_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")
    from collections import Counter

    from deeplearning4j_tpu.text.native_tokenizer import NativeCorpusEncoder
    from deeplearning4j_tpu.text.tokenization import (
        CommonPreprocessor, DefaultTokenizerFactory,
    )
    docs = ["a\x1cb c", "d\x1de\x1ef\x1fg"]
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    exp = Counter()
    for d in docs:
        exp.update(tf.tokenize(d))
    got = NativeCorpusEncoder().count_or_none(docs)
    assert got == dict(exp)


def test_native_encoder_empty_vocab_keep_oov():
    from deeplearning4j_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")
    from deeplearning4j_tpu.text.native_tokenizer import NativeCorpusEncoder
    out = NativeCorpusEncoder().encode_or_none(
        ["hello world"], {}, keep_oov=True)
    assert out is not None and list(out[0]) == [-1, -1]
