"""Positive fixture: lock-order-inversion — AB/BA acquisition cycle.

`forward()` takes a then b; `backward()` takes b then a. Run
concurrently, each thread can hold one lock and wait forever on the
other. `indirect()` shows the interprocedural half: the a->b edge via a
helper call participates in the same cycle.
"""
import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def forward():
    with _lock_a:
        with _lock_b:  # EXPECT
            pass


def forward_multi():
    # `with a, b:` acquires left to right — same a->b order as nesting
    with _lock_a, _lock_b:  # EXPECT
        pass


def backward():
    with _lock_b:
        with _lock_a:  # EXPECT
            pass


def _helper_takes_b():
    with _lock_b:
        pass


def indirect():
    with _lock_a:
        _helper_takes_b()  # EXPECT
