"""Positive fixture: resource-pairing — the literal PR-8 half-open-slot
leak. `route()` consumes a breaker probe slot with allow(); the
backpressure branch (429/503) returns with NEITHER release() nor
record_*() — the slot leaks and the breaker wedges half-open forever.
The shared-memory variant leaks the segment on an early size bailout."""
from multiprocessing.shared_memory import SharedMemory


class Router:
    def send(self):
        return 200

    def route(self, breaker):
        if not breaker.allow():
            return None
        code = self.send()
        if code in (429, 503):
            return code  # EXPECT
        if code >= 500:
            breaker.record_failure()
            return code
        breaker.record_success()
        return code


def stage_batch(arr, limit):
    shm = SharedMemory(create=True, size=arr.nbytes)
    if arr.nbytes > limit:
        return None  # EXPECT
    shm.buf[:arr.nbytes] = arr.tobytes()
    out = bytes(shm.buf[:arr.nbytes])
    shm.unlink()
    return out
