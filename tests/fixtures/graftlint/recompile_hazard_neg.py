"""graftlint fixture: recompile-hazard NEAR-MISS NEGATIVES — branches on
static facts (shape/dtype/None-ness) are fine under tracing, and value
branches OUTSIDE compiled code are plain Python. Zero findings."""
import jax
import jax.numpy as jnp


@jax.jit
def step(params, x, mask):
    if x.ndim == 3:                      # shapes are static
        x = x.reshape(x.shape[0], -1)
    if mask is not None:                 # None-ness is static
        x = x * mask
    if isinstance(params, dict):         # type is static
        params = params["w"]
    return jnp.dot(params, x.T)


def host_side(loss):
    if loss > 10.0:                      # not a compiled region
        return True
    return False
