"""graftlint fixture: recompile-hazard TRUE POSITIVES — Python branches
on traced VALUES inside jitted functions."""
import jax
import jax.numpy as jnp


@jax.jit
def clip_step(params, grads):
    if jnp.abs(grads).max() > 10.0:  # EXPECT
        grads = grads / 10.0
    return params - grads


def make_step():
    def step(params, x):
        while params.sum() > 1.0:  # EXPECT
            params = params * 0.5
        return params + x
    return jax.jit(step)
