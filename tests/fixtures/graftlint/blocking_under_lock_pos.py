"""graftlint fixture: blocking-under-lock TRUE POSITIVES, including the
PR-8 launch-under-tick-lock shape that froze fleet supervision."""
import subprocess
import threading
import time


class Supervisor:
    def __init__(self):
        self._tick_lock = threading.Lock()
        self._procs = []

    def tick(self, replica):
        # the PR-8 bug: a hung replica launch under the tick lock stalls
        # probing of the WHOLE fleet and deadlocks stop()
        with self._tick_lock:
            if not replica.alive():
                replica.relaunch(timeout=180)  # EXPECT
            time.sleep(0.5)  # EXPECT

    def drain(self, worker):
        with self._tick_lock:
            worker.join()  # EXPECT

    def spawn(self, cmd):
        with self._tick_lock:
            return subprocess.run(cmd, capture_output=True)  # EXPECT

    def probe(self, sock, addr):
        with self._tick_lock:
            sock.connect(addr)  # EXPECT
