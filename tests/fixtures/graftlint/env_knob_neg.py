"""graftlint fixture: env-knob-contract NEAR-MISS NEGATIVES.

Typed accessors, env WRITES (seeding child processes), non-DL4J_TPU
variables, and value (not flag) comparisons. Zero findings expected.
"""
import os

from deeplearning4j_tpu.util.env import env_flag, env_int, env_str, scoped


def proper_reads():
    on = env_flag("DL4J_TPU_FEATURE")
    depth = env_int("DL4J_TPU_DEPTH", 2)
    mode = env_str("DL4J_TPU_MODE", "auto")
    return on, depth, mode


def writes_are_fine(child_env):
    os.environ["DL4J_TPU_WORKERS"] = "0"          # write: allowed
    os.environ.setdefault("DL4J_TPU_SEED", "1")   # child seeding: allowed
    del os.environ["DL4J_TPU_WORKERS"]
    with scoped("DL4J_TPU_WORKERS", "4"):
        child_env.update(os.environ)


def other_namespaces():
    return os.environ.get("JAX_PLATFORMS", "cpu")  # not our namespace


def value_compare_is_fine():
    return env_str("DL4J_TPU_MODE", "auto") == "auto"   # value, not flag
