"""graftlint fixture: bare-except-swallow NEAR-MISS NEGATIVES.

Narrow types, observed failures (logged / counted / recorded), and
re-raises are all fine in process-boundary code. Zero findings.
"""
import logging

log = logging.getLogger(__name__)


def worker_loop(tasks, out_q, metrics):
    for t in tasks:
        try:
            out_q.put(t.run())
        except (OSError, ValueError):          # narrow: a decision
            continue
        except Exception:
            metrics.errors += 1                # observed: counted
            log.warning("task failed", exc_info=True)


def supervisor_tick(replicas):
    for r in replicas:
        try:
            r.probe()
        except Exception as e:
            r.last_error = e                   # observed: recorded
