"""graftlint fixture: bare-except-swallow TRUE POSITIVES.

Lives under a `parallel/` path segment — process-boundary scope. A bare
except breaks clean preemption; a broad swallow turns worker crashes
into silent hangs.
"""


def worker_loop(tasks, out_q):
    for t in tasks:
        try:
            out_q.put(t.run())
        except:  # EXPECT
            continue


def supervisor_tick(replicas):
    for r in replicas:
        try:
            r.probe()
        except Exception:  # EXPECT
            pass
