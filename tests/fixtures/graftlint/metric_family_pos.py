"""graftlint fixture: metric-family-registration TRUE POSITIVES.

Emits `*_total` / `*_seconds` families missing from the (injected)
catalog — operators alert on the catalog, not the code.
"""
from deeplearning4j_tpu import monitor


def record(dt):
    monitor.counter("fixture_undocumented_total", "not in catalog").inc()  # EXPECT
    monitor.histogram("fixture_undocumented_seconds", "nope").observe(dt)  # EXPECT
    # documented family next to the undocumented ones
    monitor.counter("fixture_documented_total", "in catalog").inc()
