"""graftlint fixture: env-knob-contract TRUE POSITIVES.

Raw DL4J_TPU_* reads bypassing util/env.py, the shipped `!= '1'` /
`== '1'` truthiness bugs, and hand-rolled flag logic on accessor
results.
"""
import os

from deeplearning4j_tpu.util.env import env_str


def scattered_reads():
    a = os.environ.get("DL4J_TPU_THING", "1")  # EXPECT
    b = os.environ["DL4J_TPU_OTHER"]  # EXPECT
    c = os.getenv("DL4J_TPU_THIRD")  # EXPECT
    return a, b, c


def shipped_bug_shapes():
    # '' disables a default-on feature (PR-7 FIT_PREFETCH bug)
    on = os.environ.get("DL4J_TPU_FEATURE", "") != "1"  # EXPECT
    # 'true' disables a default-on feature (PR-5 DEVICE_NORM bug)
    also_on = os.environ.get("DL4J_TPU_FEATURE2", "1") == "1"  # EXPECT
    return on, also_on


def handrolled_on_accessor():
    return env_str("DL4J_TPU_FLAGGY") == "1"  # EXPECT


def read_through_setdefault():
    return int(os.environ.setdefault("DL4J_TPU_DEPTH", "2"))  # EXPECT
