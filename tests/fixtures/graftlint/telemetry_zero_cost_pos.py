"""graftlint fixture: telemetry-zero-cost TRUE POSITIVES.

Telemetry inside compiled code records once at trace time; expensive
span attrs are evaluated eagerly even while tracing is disabled.
"""
import jax

from deeplearning4j_tpu import monitor


@jax.jit
def step(params, x):
    with monitor.span("train/inner"):  # EXPECT
        y = params @ x
    monitor.counter("steps_total", "steps").inc()  # EXPECT
    return y


@jax.jit
def decode_step(params, x, ctx):
    # a flight-recorder event in a compiled region records once at
    # trace time — the black box would be blind at runtime
    monitor.flight.note(ctx, "page_stall", slot=0)  # EXPECT
    return params @ x


def fit_loop(batches, step_fn):
    for b in batches:
        loss = step_fn(b)
        # float(loss) runs even while tracing is disabled: an always-on
        # device->host sync smuggled in through span attrs
        with monitor.span("train/step", loss=float(loss)):  # EXPECT
            pass
