"""graftlint fixture: unlaundered-restore-placement TRUE POSITIVES.

Deserialized values device_put onto explicit placements without going
through util/params.own_tree — the sharding-aware PR-3 segfault shape.
Lines expected to be flagged carry an EXPECT marker comment.
"""
import pickle

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as fser
from jax.sharding import NamedSharding, PartitionSpec as P


def restore_params(zf, mesh, template):
    loaded = np.load(zf)
    return jax.device_put(loaded, NamedSharding(mesh, P("data")))  # EXPECT


def restore_updater(blob, template, sharding):
    opt_state = fser.from_bytes(template, blob)
    return jax.device_put(opt_state, sharding)  # EXPECT


def restore_via_alias(path, dev):
    tree = pickle.load(open(path, "rb"))
    placed = tree            # simple-name propagation keeps the taint
    aliased = jnp.asarray(placed)   # zero-copy: transports the taint
    return jax.device_put(aliased, dev)  # EXPECT
