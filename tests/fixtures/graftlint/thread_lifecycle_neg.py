"""Negative fixture: thread-lifecycle near-misses that must stay clean.

- a guarded loop target (the PR-11 FIX shape), named, daemonized;
- a non-daemon thread joined in close();
- a spawn helper given the name positionally (fleet's _threaded_spawn
  convention);
- an opaque stdlib target (serve_forever) that cannot be analyzed —
  named, so nothing fires.
"""
import threading


def _threaded_spawn(fn, name):
    t = threading.Thread(target=fn, daemon=True, name=name)
    t.start()
    return t


class Scheduler:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-scheduler")
        self._thread.start()

    def _loop(self):
        try:
            while True:
                self._admit()
        except Exception:
            self._fail_all()

    def _admit(self):
        pass

    def _fail_all(self):
        pass


class Writer:
    def __init__(self):
        self._writer = threading.Thread(target=self._run, name="writer")
        self._writer.start()

    def _run(self):
        try:
            self._write()
        except Exception:
            pass

    def _write(self):
        pass

    def close(self):
        self._writer.join(timeout=5)


class Helper:
    def relaunch(self, replica):
        return _threaded_spawn(lambda: self._do(replica),
                               f"relaunch-{replica}")

    def _do(self, replica):
        try:
            pass
        except Exception:
            pass


class Server:
    def __init__(self, httpd):
        self._httpd = httpd
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="http-server")
        self._thread.start()
