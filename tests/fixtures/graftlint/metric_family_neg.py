"""graftlint fixture: metric-family-registration NEAR-MISS NEGATIVES.

Cataloged families pass; gauges and non-contract suffixes are outside
the `*_total`/`*_seconds` contract. Zero findings.
"""
from deeplearning4j_tpu import monitor


def record(dt, depth):
    monitor.counter("fixture_documented_total", "in catalog").inc()
    monitor.histogram("fixture_documented_seconds", "in catalog").observe(dt)
    monitor.gauge("fixture_queue_depth", "gauge: no suffix contract").set(depth)
