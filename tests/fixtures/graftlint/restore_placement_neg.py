"""graftlint fixture: unlaundered-restore-placement NEAR-MISSES.

All of these must stay clean: the laundering helpers, explicit copies,
placements of non-deserialized values, and device_puts without an
explicit placement.
"""
import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as fser

from deeplearning4j_tpu.util.params import own_tree, owned_leaf


def restore_laundered(blob, template, shardings):
    # the blessed path: sharding-aware own_tree copies FIRST, then places
    return own_tree(fser.from_bytes(template, blob), shardings)


def restore_leaf_laundered(arr, sharding):
    restored = np.load(arr)
    return owned_leaf(restored, sharding)


def restore_copied_then_placed(zf, sharding):
    loaded = np.load(zf)
    owned = jnp.array(loaded, copy=True)   # explicit copy clears taint
    return jax.device_put(owned, sharding)


def stage_batch(batch, sharding):
    # plain batch staging: not deserialized, never donated — fine
    arr = np.stack([b for b in batch])
    return jax.device_put(arr, sharding)


def plain_put_no_placement(blob, template):
    # no explicit placement named: the donated-aliasing rule owns this
    restored = fser.from_bytes(template, blob)
    return jax.device_put(restored)


def relaundered_name(path, dev):
    tree = np.load(path)
    tree = own_tree(tree)       # re-assignment clears the taint
    return jax.device_put(tree, dev)
