"""Positive fixture: transitive-blocking-under-lock — the literal PR-8
supervisor shape. tick() holds the tick lock and calls _restart();
_restart() calls _boot(); _boot() blocks on a subprocess spawn + wait.
Nothing blocking is LEXICALLY inside the `with` — the pre-PR lexical
blocking-under-lock rule sees nothing here (pinned by
test_transitive_fixture_invisible_to_lexical_rule); only the call-graph
walk finds it."""
import subprocess
import threading


class Supervisor:
    def __init__(self):
        self._tick_lock = threading.Lock()
        self.proc = None

    def _boot(self):
        self.proc = subprocess.Popen(["sleep", "5"])

    def _restart(self):
        self._boot()

    def tick(self):
        with self._tick_lock:
            self._restart()  # EXPECT

    def tick_two_hops(self):
        with self._tick_lock:
            probe_and_restart(self)  # EXPECT


def probe_and_restart(sup):
    _spawn_process()


def _spawn_process():
    subprocess.Popen(["sleep", "5"])
