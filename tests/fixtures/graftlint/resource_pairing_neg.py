"""Negative fixture: resource-pairing near-misses that must stay clean.

- releases inside try/finally pay every path at once;
- denied-acquire branches hold nothing (`if not allow(): return`,
  `info is None` admission failures);
- a release-before-exit on the same branch;
- a pure cross-function protocol (acquire here, release in finish())
  is out of scope and stays silent.
"""
from multiprocessing.shared_memory import SharedMemory


class Router:
    def send(self):
        return 200

    def route_finally(self, breaker):
        if not breaker.allow():
            return None
        try:
            code = self.send()
            if code in (429, 503):
                return code
            return code
        finally:
            breaker.release()

    def route_released_branch(self, breaker):
        if not breaker.allow():
            return None
        code = self.send()
        if code in (429, 503):
            breaker.release()
            return code
        breaker.record_success()
        return code


class Engine:
    def admit(self, cache, prompt):
        info = cache.admit_prompt(prompt)
        if info is None:
            return None       # denied admission: nothing held
        cache.release(info)
        return info


class Scheduler:
    """Cross-function protocol: admit here, release in finish() — the
    per-function rule deliberately stays silent."""

    def admit(self, cache, n):
        self.slot = cache.admit(n)
        return self.slot

    def finish(self, cache):
        cache.release(self.slot)


def stage_batch(arr, limit):
    shm = SharedMemory(create=True, size=arr.nbytes)
    try:
        if arr.nbytes > limit:
            return None
        return bytes(shm.buf[:arr.nbytes])
    finally:
        shm.unlink()
