"""graftlint fixture: donated-aliasing TRUE POSITIVE (module contract).

A module that builds donating programs but never launders host buffers
through util/params.own_tree — every donation site must be flagged.
Lines expected to be flagged carry an EXPECT marker comment.
"""
import jax
import numpy as np


def make_step(step):
    return jax.jit(step, donate_argnums=(0, 1))  # EXPECT


def stage(x, dev):
    return jax.device_put(x, dev, donate=True)  # EXPECT
