"""Negative fixture: transitive-blocking-under-lock near-misses.

- the blocking work moved OUTSIDE the critical section (the PR-8 fix
  shape: collect under the lock, act after it);
- a nested def inside the region (runs on its own thread, not under
  the lock);
- `with cv: cv.wait()` (condition variables are not lock-ish);
- a helper that only does cheap dict work.
"""
import subprocess
import threading


class Supervisor:
    def __init__(self):
        self._tick_lock = threading.Lock()
        self.due = []
        self.proc = None

    def _boot(self):
        self.proc = subprocess.Popen(["sleep", "5"])

    def _bookkeep(self):
        self.due.append(1)

    def tick(self):
        due = []
        with self._tick_lock:
            self._bookkeep()          # cheap: no blocking reachable
            due.extend(self.due)

            def _spawned_later():
                # nested def: runs on its own activation, not under
                # the lock the enclosing frame holds
                self._boot()
        for _ in due:
            self._boot()              # blocking, but the lock is gone


def condition_wait(cv=threading.Condition()):
    with cv:
        cv.wait()
