"""graftlint fixture: telemetry-zero-cost NEAR-MISS NEGATIVES.

Cheap attrs (names, shapes, len) ride span() directly; expensive attrs
are fine under the tracing_enabled() guard; telemetry in the HOST loop
around the compiled call is the correct placement. Zero findings.
"""
import jax

from deeplearning4j_tpu import monitor


@jax.jit
def step(params, x):
    return params @ x


def fit_loop(batches, step_fn, net):
    for b in batches:
        with monitor.span("train/step", n=int(b.shape[0]),
                          requests=len(batches), name=net.name):
            loss = step_fn(b)
        if monitor.tracing_enabled():
            # guarded: the sync costs only when someone is watching
            monitor.span("train/loss_probe", loss=float(loss)).__enter__()
        monitor.counter("steps_total", "steps").inc()


def scheduler_loop(reqs, step_fn, ctx, log):
    for r in reqs:
        step_fn(r)
        # flight events from the HOST loop are the correct placement
        monitor.flight.note(ctx, "admitted", slot=0)
        # a non-flight object's .note()/.record() must not match
        log.note("admitted")
        log.record("something")
