"""graftlint fixture: donated-aliasing NEAR-MISS NEGATIVE.

Donating programs in a module that launders restored state through
util/params.own_tree before the first donation — the fixed PR-3 shape.
Zero findings expected.
"""
import numpy as np
import jax

from deeplearning4j_tpu.util.params import own_tree


class Trainer:
    def build(self, step):
        self._step = jax.jit(step, donate_argnums=(0,))

    def resume(self, path):
        restored = own_tree(np.load(path))   # XLA-owned copies
        return self._step(restored)
