"""graftlint fixture: donated-aliasing TRUE POSITIVE — the PR-3
serde-resume segfault shape.

Checkpoint-restored (numpy-backed) params flow into a donating jitted
step WITHOUT passing through own_tree. The module references own_tree
(so the module-level contract check passes) — only the lightweight
dataflow check can catch this, which is exactly what PR 3 shipped.
"""
import numpy as np
import jax

from deeplearning4j_tpu.util.params import own_tree


class Trainer:
    def build(self, step):
        self._step = jax.jit(step, donate_argnums=(0,))

    def resume(self, path):
        # numpy-backed leaves straight off disk: XLA does NOT own this
        # memory, and the donating step below will free/reuse it
        restored = np.load(path)
        loss = self._step(restored)  # EXPECT
        return loss

    def resume_via_asarray(self, path):
        # jnp.asarray on numpy is ZERO-COPY on CPU: it TRANSPORTS the
        # alias, it does not launder it — the exact PR-3 mechanism
        staged = jax.numpy.asarray(np.load(path))
        return self._step(staged)  # EXPECT

    def resume_safely(self, path):
        restored = own_tree(np.load(path))
        return self._step(restored)   # laundered: not flagged
