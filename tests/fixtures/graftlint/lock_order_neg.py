"""Negative fixture: lock-order-inversion near-misses that must stay
clean — a globally consistent order, condition variables, and
re-entrant self-acquisition."""
import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()
_cv = threading.Condition()


def consistent_one():
    # a -> b here AND below: one global order, no cycle
    with _lock_a:
        with _lock_b:
            pass


def consistent_two():
    with _lock_a:
        with _lock_b:
            pass


def condition_wait():
    # `with cv: cv.wait()` is the correct idiom — cv is not lock-ish
    with _cv:
        _cv.wait()


class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()

    def _inner(self):
        with self._lock:
            pass

    def outer(self):
        # re-entrant self-acquire is not an ORDER between two locks
        with self._lock:
            self._inner()
