"""graftlint fixture: host-sync-in-hot-path NEAR-MISS NEGATIVES.

Shape/len reads are static under tracing; host-side numpy parsing in a
fit loop is legitimate ETL; a float() on a CONSTANT is not a sync.
Zero findings expected.
"""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(params, x):
    n = int(x.shape[0])          # static fact, no transfer
    k = len(params)              # static fact
    return jnp.dot(params, x) / n * k


def outside_hot_path(y):
    return float(y[0])           # not in a compiled region / fit loop


class Net:
    def fit(self, batches, step_fn):
        for b in batches:
            feats = np.asarray(b.features, dtype="float32")  # host ETL
            lr = float("1e-3")   # constant, not a device value
            self.last = step_fn(feats, lr)
