"""Positive fixture: thread-lifecycle — three spawn sites, one defect
each (one finding per line, so the EXPECT golden stays exact).

`Scheduler` is the literal PR-11 shape: the decode scheduler's loop —
the only thread that reclaims slots — with NO top-level exception
guard; one admission error kills it silently while the servable keeps
answering /readyz 200. The lexical PR-9 rules have nothing to say about
it (pinned by test_thread_fixture_invisible_to_lexical_rules).
"""
import threading


class Scheduler:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop,  # EXPECT
                                        daemon=True,
                                        name="decode-scheduler")
        self._thread.start()

    def _loop(self):
        while True:
            self._admit()
            self._step_all()

    def _admit(self):
        pass

    def _step_all(self):
        pass


class Prober:
    def __init__(self):
        self._t = threading.Thread(target=self._probe, daemon=True)  # EXPECT
        self._t.start()

    def _probe(self):
        while True:
            try:
                self._one()
            except Exception:
                return

    def _one(self):
        pass


class Flusher:
    """Non-daemon, stored on self, and no teardown method ever joins
    it: interpreter exit blocks forever on a forgotten flush loop."""

    def __init__(self):
        self._flusher = threading.Thread(target=self._run,  # EXPECT
                                         name="flusher")
        self._flusher.start()

    def _run(self):
        while True:
            try:
                self._flush()
            except Exception:
                return

    def _flush(self):
        pass

    def stop(self):
        pass          # forgets self._flusher.join()
