"""graftlint fixture: blocking-under-lock NEAR-MISS NEGATIVES.

str.join under a lock is not a thread join; work scheduled via a nested
def does NOT run while the lock is held (the PR-8 fix moved launches
into exactly such spawn threads); condition-variable wait is the
correct idiom; blocking calls OUTSIDE the critical section are fine.
Zero findings expected.
"""
import threading
import time


class Supervisor:
    def __init__(self):
        self._tick_lock = threading.Lock()
        self._cv = threading.Condition()

    def describe(self, parts):
        with self._tick_lock:
            return ", ".join(parts)        # str.join, not thread join

    def tick(self, replica):
        with self._tick_lock:
            # the PR-8 FIX shape: the launch runs on a spawn thread,
            # not under the lock
            def relaunch_off_lock():
                time.sleep(0.5)
                replica.relaunch(timeout=180)
            t = threading.Thread(target=relaunch_off_lock, daemon=True)
            t.start()
        t.join()                           # outside the critical section

    def wait_for_work(self):
        with self._cv:
            self._cv.wait()                # the Condition idiom
