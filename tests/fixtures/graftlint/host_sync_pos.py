"""graftlint fixture: host-sync-in-hot-path TRUE POSITIVES.

Device->host syncs inside compiled regions and an extra sync inside a
fit inner loop.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def step(params, x):
    y = jnp.dot(params, x)
    scale = float(y[0])  # EXPECT
    return y * scale


def scan_pipeline(xs, carry0):
    def body(carry, x):
        v = carry + x
        host = v.item()  # EXPECT
        return v, host
    return lax.scan(body, carry0, xs)


class Net:
    def fit(self, batches, step_fn):
        for b in batches:
            params, loss = step_fn(b)
            probe = float(loss)  # EXPECT
            extra = float(loss)  # EXPECT
            self.history.append((probe, extra))
