"""Model-serving subsystem tests (serving/ registry + batcher + server).

Covers the acceptance contract: registry load/verify/swap/rollback,
bucket padding with at-most-once-compile-per-bucket, 429 under a
saturated queue, expired deadline -> 504, the live healthz -> readyz ->
predict -> swap-under-traffic round trip, and serving_* families on the
server's own /metrics. Small FF nets keep CPU compiles sub-second; the
zoo-LeNet end-to-end lives in tools/serve_smoke.py.
"""
import json
import logging
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (
    DeadlineExceededError, ModelLoadError, ModelRegistry, ModelServer,
    ServerOverloadedError, ShapeBucketedBatcher, load_servable,
)

N_IN, N_OUT = 6, 3


def _net(seed=0):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _deploy(registry, name="m", seed=0, **kw):
    kw.setdefault("buckets", (1, 4, 16))
    kw.setdefault("max_delay_ms", 2.0)
    return registry.deploy(name, _net(seed), **kw)


def _post(url, body: bytes, timeout=30, ctype="application/json"):
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": ctype})
    r = urllib.request.urlopen(req, timeout=timeout)
    return r.status, json.loads(r.read())


@pytest.fixture
def registry():
    reg = ModelRegistry()
    yield reg
    reg.shutdown(drain=False)


# ---------------------------------------------------------------- registry
def test_load_servable_sources(tmp_path):
    from deeplearning4j_tpu.util.serialization import save_model
    # live object passes through
    net = _net()
    assert load_servable(net) is net
    # save_model zip
    path = str(tmp_path / "m.zip")
    save_model(net, path)
    loaded = load_servable(path)
    x = np.random.RandomState(0).randn(2, N_IN).astype("float32")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(loaded.output(x)), atol=1e-6)
    # zoo: name resolution (no init — that's model_by_name's caller)
    from deeplearning4j_tpu.models import model_by_name
    assert type(model_by_name("lenet")).__name__ == "LeNet"
    with pytest.raises(KeyError):
        model_by_name("NoSuchArch")
    # unknown path
    with pytest.raises(ModelLoadError):
        load_servable(str(tmp_path / "missing.zip"))


def test_load_servable_checkpoint_dir_verifies_sha(tmp_path):
    """Manifest-directory source: newest SHA-256-verified entry wins; a
    corrupted newest checkpoint falls back to the next-newest."""
    from deeplearning4j_tpu.train.resilience import CheckpointManager
    ckdir = str(tmp_path / "ckpts")
    mgr = CheckpointManager(ckdir, keep_last=3)
    net_a, net_b = _net(1), _net(2)
    mgr.save(net_a, {"step_in_epoch": 0})
    path_b = mgr.save(net_b, {"step_in_epoch": 0})
    x = np.random.RandomState(0).randn(2, N_IN).astype("float32")
    # newest (net_b) loads
    loaded = load_servable(ckdir)
    np.testing.assert_allclose(np.asarray(loaded.output(x)),
                               np.asarray(net_b.output(x)), atol=1e-6)
    # corrupt newest -> falls back to net_a
    with open(path_b, "r+b") as f:
        f.seek(20)
        f.write(b"\xde\xad\xbe\xef")
    loaded = load_servable(ckdir)
    np.testing.assert_allclose(np.asarray(loaded.output(x)),
                               np.asarray(net_a.output(x)), atol=1e-6)
    # empty/never-valid dir
    with pytest.raises(ModelLoadError):
        load_servable(str(tmp_path / "empty"))


def test_registry_swap_and_rollback(registry):
    served = _deploy(registry, seed=0)
    x = np.random.RandomState(0).randn(3, N_IN).astype("float32")
    y1 = served.predict(x)
    info = served.swap(_net(1))
    assert info["version"] == 2
    y2 = served.predict(x)
    assert not np.allclose(y1, y2, atol=1e-6)
    info = served.rollback()
    assert info["version"] == 1
    np.testing.assert_allclose(served.predict(x), y1, atol=1e-6)
    # rollback below the history floor is a clean error
    with pytest.raises(ModelLoadError):
        served.rollback()


def test_swap_rejects_incompatible_input_shape(registry):
    served = _deploy(registry)
    wide = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN + 1)).build())
    with pytest.raises(ModelLoadError, match="swap rejected"):
        served.swap(MultiLayerNetwork(wide).init())
    # still serving v1 afterwards
    assert served.versions[served.active].version == 1
    served.predict(np.zeros((2, N_IN), "float32"))


# ----------------------------------------------------------------- batcher
def test_bucket_padding_correct_and_compiles_once(registry):
    monitor.REGISTRY.reset()
    served = _deploy(registry, seed=3)
    net = served.versions[0].model
    rs = np.random.RandomState(1)
    for n in (1, 2, 3, 4, 5, 7, 11, 16):
        x = rs.randn(n, N_IN).astype("float32")
        y = served.predict(x)
        assert y.shape == (n, N_OUT)
        np.testing.assert_allclose(y, np.asarray(net.output(x)), atol=1e-5)
    # ledger: every bucket compiled exactly once (at warmup), and the
    # varied request sizes above added NO request-path compiles
    fam = monitor.REGISTRY.collect("serving_bucket_compiles_total")
    for b in served.batcher.buckets:
        assert fam.value(model="m", bucket=str(b)) == 1
    warmups = monitor.REGISTRY.collect("serving_warmup_runs_total")
    assert warmups.value(model="m") == len(served.batcher.buckets)


def test_bucket_oversize_request_chunks_to_ladder(registry):
    monitor.REGISTRY.reset()
    served = _deploy(registry, seed=4)      # max bucket 16
    net = served.versions[0].model
    x = np.random.RandomState(2).randn(41, N_IN).astype("float32")
    y = served.predict(x)
    assert y.shape == (41, N_OUT)
    np.testing.assert_allclose(y, np.asarray(net.output(x)), atol=1e-5)
    fam = monitor.REGISTRY.collect("serving_bucket_compiles_total")
    total = sum(fam.value(model="m", bucket=str(b))
                for b in served.batcher.buckets)
    assert total == len(served.batcher.buckets)     # chunking, no new shape


def test_batcher_coalesces_concurrent_requests():
    """Concurrent callers coalesce into one device batch (run-count < N)."""
    runs = []

    def runner(x):
        runs.append(x.shape[0])
        time.sleep(0.01)
        return x * 2.0

    with ShapeBucketedBatcher(runner, (N_IN,), buckets=(1, 4, 16),
                              max_delay_ms=25.0, name="co") as b:
        b.warm()
        runs.clear()
        outs = [None] * 8

        def call(i):
            outs[i] = b.predict(np.full((1, N_IN), float(i), "float32"))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(8):
            np.testing.assert_allclose(outs[i], np.full((1, N_IN),
                                                        2.0 * i), atol=0)
        assert len(runs) < 8            # coalescing actually happened


def test_batcher_queue_full_raises_overload():
    release = threading.Event()

    def slow_runner(x):
        release.wait(5)
        return x

    with ShapeBucketedBatcher(slow_runner, (N_IN,), buckets=(1,),
                              max_delay_ms=0.0, queue_limit=2,
                              name="oq") as b:
        def quiet_predict():
            try:
                b.predict(np.zeros((1, N_IN), "float32"))
            except Exception:  # noqa: BLE001 — races are the main path's
                pass

        # stall the worker on the first request...
        stalled = threading.Thread(target=quiet_predict, daemon=True)
        stalled.start()
        time.sleep(0.2)
        # ...fill the bounded queue behind it...
        waiters = [threading.Thread(target=quiet_predict, daemon=True)
                   for _ in range(2)]
        for t in waiters:
            t.start()
        time.sleep(0.2)
        # ...then require explicit backpressure, not silent queueing
        with pytest.raises(ServerOverloadedError):
            b.predict(np.zeros((1, N_IN), "float32"))
        release.set()
        stalled.join(timeout=5)
        for t in waiters:
            t.join(timeout=5)


def test_batcher_deadline_expired_in_queue():
    def slow_runner(x):
        time.sleep(0.3)
        return x

    with ShapeBucketedBatcher(slow_runner, (N_IN,), buckets=(1,),
                              max_delay_ms=0.0, name="dl") as b:
        t1 = threading.Thread(
            target=lambda: b.predict(np.zeros((1, N_IN), "float32")),
            daemon=True)
        t1.start()                       # occupies the worker ~0.3s
        time.sleep(0.05)
        with pytest.raises(DeadlineExceededError):
            b.predict(np.zeros((1, N_IN), "float32"), deadline=0.05)
        t1.join(timeout=5)


# ------------------------------------------------------------------ server
@pytest.fixture
def server(registry):
    _deploy(registry, seed=0)
    srv = ModelServer(registry, port=0, default_deadline_s=30.0)
    yield srv
    srv.stop()


def test_server_predict_json_and_npy(server):
    url = f"{server.url}/v1/models/m/predict"
    x = np.random.RandomState(0).randn(3, N_IN).astype("float32")
    code, out = _post(url, json.dumps({"inputs": x.tolist()}).encode())
    assert code == 200 and out["version"] == 1
    assert np.asarray(out["outputs"]).shape == (3, N_OUT)
    # npy in, npy out
    import io
    buf = io.BytesIO()
    np.save(buf, x, allow_pickle=False)
    req = urllib.request.Request(
        url, data=buf.getvalue(),
        headers={"Content-Type": "application/octet-stream",
                 "Accept": "application/octet-stream"})
    r = urllib.request.urlopen(req, timeout=30)
    y = np.load(io.BytesIO(r.read()), allow_pickle=False)
    assert y.shape == (3, N_OUT)
    # single unbatched example round-trips unbatched
    code, out = _post(url, json.dumps(
        {"inputs": x[0].tolist()}).encode())
    assert np.asarray(out["outputs"]).shape == (N_OUT,)


def test_server_clean_errors_never_traceback(server):
    url = server.url
    # unknown model -> 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{url}/v1/models/nope/predict", b'{"inputs": [[1]]}')
    assert e.value.code == 404 and "error" in json.loads(e.value.read())
    # malformed body -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{url}/v1/models/m/predict", b"not json")
    assert e.value.code == 400
    # wrong feature width -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{url}/v1/models/m/predict",
              json.dumps({"inputs": [[1.0, 2.0]]}).encode())
    assert e.value.code == 400
    # bad swap body -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{url}/v1/models/m/swap", b"{}")
    assert e.value.code == 400


def test_server_deadline_504(server, registry):
    served = registry.get("m")
    real = served.batcher.runner
    served.batcher.runner = lambda x: (time.sleep(0.2), real(x))[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{server.url}/v1/models/m/predict?deadline_ms=0.01",
                  json.dumps({"inputs": np.zeros((1, N_IN)).tolist()}
                             ).encode())
        assert e.value.code == 504
        assert "error" in json.loads(e.value.read())
    finally:
        served.batcher.runner = real


def test_server_saturated_queue_429(registry):
    served = _deploy(registry, name="sat", queue_limit=2)
    release = threading.Event()
    real = served.batcher.runner
    served.batcher.runner = lambda x: (release.wait(10), real(x))[1]
    # short default deadline: a probe that DOES get admitted behind the
    # stalled worker 504s quickly instead of hanging out its socket
    srv = ModelServer(registry, port=0, default_deadline_s=0.5)
    try:
        url = f"{srv.url}/v1/models/sat/predict"
        body = json.dumps({"inputs": np.zeros((1, N_IN)).tolist()}).encode()

        def quiet_post():
            try:
                _post(url, body, timeout=30)
            except Exception:  # noqa: BLE001 — a racy 429 here is fine too
                pass

        threads = [threading.Thread(target=quiet_post, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)                  # worker stalled, queue filling
        saw_429 = False
        for _ in range(8):
            try:
                _post(url, body, timeout=5)
            except urllib.error.HTTPError as e:
                e.read()
                if e.code == 429:
                    saw_429 = True
                    # derived + jittered, never the old constant stampede
                    # magnet: an integer in the [1, 5] ceiling range
                    assert 1 <= int(e.headers.get("Retry-After")) <= 5
                    break
            except Exception:  # noqa: BLE001 — admitted probe timed out
                pass           # behind the stall; keep probing for the 429
        assert saw_429
        release.set()
        for t in threads:
            t.join(timeout=10)
    finally:
        release.set()
        served.batcher.runner = real
        srv.stop()


def test_health_ready_swap_under_traffic_round_trip(registry):
    """The acceptance round trip: healthz -> readyz -> predict under
    concurrent load -> hot-swap -> rollback mid-traffic, zero failures."""
    _deploy(registry, name="rt", seed=0)
    srv = ModelServer(registry, port=0)
    try:
        url = srv.url
        assert urllib.request.urlopen(f"{url}/healthz",
                                      timeout=10).status == 200
        assert urllib.request.urlopen(f"{url}/readyz",
                                      timeout=10).status == 200
        predict = f"{url}/v1/models/rt/predict"
        rs = np.random.RandomState(0)
        bodies = [json.dumps({"inputs": rs.rand(b, N_IN).tolist()}).encode()
                  for b in (1, 2, 4)]
        results = {"ok": 0, "fail": []}
        lock = threading.Lock()
        versions = set()
        # event-gated so the traffic deterministically SPANS the swap
        # window: on a fast box all 80 predicts used to finish before
        # the swap landed (versions == {1}, flaky). Workers hold half
        # their requests until the swap returned, and the rollback
        # waits until some predict actually observed v2.
        swap_live = threading.Event()
        seen_v2 = threading.Event()

        def worker(k):
            for i in range(20):
                if i == 10:
                    swap_live.wait(timeout=60)
                try:
                    code, out = _post(predict, bodies[(k + i) % 3])
                    with lock:
                        results["ok"] += 1
                        versions.add(out["version"])
                    if out["version"] == 2:
                        seen_v2.set()
                except Exception as e:  # noqa: BLE001
                    with lock:
                        results["fail"].append(repr(e))

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        from deeplearning4j_tpu.util.serialization import save_model
        import tempfile, os
        v2 = os.path.join(tempfile.mkdtemp(prefix="srvt_"), "v2.zip")
        save_model(_net(9), v2)
        code, _ = _post(f"{url}/v1/models/rt/swap",
                        json.dumps({"source": v2}).encode(), timeout=60)
        assert code == 200
        swap_live.set()
        assert seen_v2.wait(timeout=60), "no predict observed v2 live"
        code, _ = _post(f"{url}/v1/models/rt/rollback", b"{}", timeout=60)
        assert code == 200
        for t in threads:
            t.join(timeout=60)
        assert results["fail"] == []
        assert results["ok"] == 80
        assert 2 in versions             # the swap was observed live
    finally:
        srv.stop()


def test_metrics_families_on_server(server):
    _post(f"{server.url}/v1/models/m/predict",
          json.dumps({"inputs": np.zeros((2, N_IN)).tolist()}).encode())
    text = urllib.request.urlopen(f"{server.url}/metrics",
                                  timeout=10).read().decode()
    for fam in ("serving_requests_total", "serving_request_seconds",
                "serving_batch_size", "serving_queue_depth",
                "serving_bucket_compiles_total",
                "serving_warmup_runs_total", "serving_model_ready"):
        assert fam in text, f"missing {fam} on /metrics"
    assert 'serving_requests_total{model="m",code="200"}' in text


def test_drain_flips_readyz_and_flushes(registry):
    from deeplearning4j_tpu.serving import ServerDrainingError
    served = _deploy(registry, name="dr")
    srv = ModelServer(registry, port=0)
    url = srv.url
    assert urllib.request.urlopen(f"{url}/readyz", timeout=10).status == 200
    srv.drain(timeout=10)
    assert srv.draining and not srv.ready()
    # the batcher stopped admitting — no request can sneak in post-drain
    with pytest.raises(ServerDrainingError):
        served.predict(np.zeros((1, N_IN), "float32"))


def test_retry_after_derived_from_queue_and_jittered(registry):
    """The 429/503 Retry-After header derives from queue fullness and is
    jittered per response (no synchronized client retry stampede): a
    saturated queue must produce spread across the [1, ceiling] range."""
    import random as _random

    from deeplearning4j_tpu.serving.batcher import _Request

    served = _deploy(registry, name="ra", queue_limit=8)
    srv = ModelServer(registry, port=0,
                      retry_jitter=_random.Random(7))
    release = threading.Event()
    entered = threading.Event()
    real = served.batcher.runner
    try:
        # stall the worker inside the runner, then stuff the queue to the
        # brim directly — exact, reproducible queue depth, no HTTP races
        def stall_runner(x):
            entered.set()
            release.wait(10)
            return real(x)

        served.batcher.runner = stall_runner
        stalled = threading.Thread(
            target=lambda: served.predict(np.zeros((1, N_IN), "float32")),
            daemon=True)
        stalled.start()
        assert entered.wait(10)            # worker now inside the stall
        for _ in range(8):
            served.batcher._queue.put_nowait(
                _Request(np.zeros((1, N_IN), "float32"), None))
        # full queue -> ceiling 5, jittered draws spread over [1, 5]
        values = {int(srv.retry_after(served)) for _ in range(40)}
        assert values <= {1, 2, 3, 4, 5} and len(values) >= 3, values
        # and the live HTTP 429 carries one of those derived values
        url = f"{srv.url}/v1/models/ra/predict"
        body = json.dumps({"inputs": np.zeros((1, N_IN)).tolist()}).encode()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, body, timeout=10)
        assert e.value.code == 429
        assert 1 <= int(e.value.headers["Retry-After"]) <= 5
        e.value.read()
        # empty queue, not draining -> always the 1s floor
        release.set()
        stalled.join(timeout=10)
        for _ in range(600):               # generous: loaded CI boxes
            if served.batcher._queue.empty():
                break
            time.sleep(0.05)
        assert served.batcher._queue.empty(), "batcher never drained"
        assert {int(srv.retry_after(served)) for _ in range(20)} == {1}
    finally:
        release.set()
        served.batcher.runner = real
        srv.stop()
    # draining server: readyz 503 carries the flat drain horizon
    srv2 = ModelServer(registry, port=0)
    try:
        srv2.draining = True
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{srv2.url}/readyz", timeout=10)
        assert e.value.code == 503
        assert 1 <= int(e.value.headers["Retry-After"]) <= 5
        e.value.read()
    finally:
        srv2.draining = False
        srv2.stop()


def test_drain_race_inflight_predict_and_swap_never_5xx_or_hang(registry):
    """The graceful-drain race matrix: concurrent SIGTERM-equivalent
    drain + in-flight predicts + a hot-swap must produce only
    {200, 429, 503, 504} (never a 500-class server error) and every
    socket must complete — no request may hang past its timeout and no
    connection may be torn mid-response."""
    _deploy(registry, name="race", seed=0)
    srv = ModelServer(registry, port=0, default_deadline_s=5.0)
    url = f"{srv.url}/v1/models/race/predict"
    rs = np.random.RandomState(0)
    bodies = [json.dumps({"inputs": rs.rand(b, N_IN).tolist()}).encode()
              for b in (1, 2, 4)]
    outcomes = []
    violations = []
    lock = threading.Lock()
    start = threading.Barrier(8 + 2, timeout=10)
    drain_started = [None]                  # wall time the drain began

    def predictor(k):
        start.wait()
        for i in range(15):
            try:
                code, _ = _post(url, bodies[(k + i) % 3], timeout=15)
                kind = code
            except urllib.error.HTTPError as e:
                e.read()
                kind = e.code
            except Exception as e:  # noqa: BLE001
                # connection-level outcome. AFTER the drain began, the
                # contract moved to the balancer (/readyz went 503):
                # clients that keep hammering a stopping listener get
                # refused/reset — acceptable. BEFORE it: a violation.
                ds = drain_started[0]
                if ds is not None and time.monotonic() >= ds:
                    kind = f"conn_after_drain:{type(e).__name__}"
                else:
                    kind = f"violation:{type(e).__name__}"
            with lock:
                outcomes.append(kind)
                if isinstance(kind, str) and kind.startswith("violation"):
                    violations.append(kind)

    # pinned BEFORE the race: the registry pops the servable at drain
    # start, so a late registry.get() would race get-vs-undeploy (None)
    # instead of the swap-vs-drain contract under test — ServedModel.swap
    # on a draining servable must raise ServerDrainingError either way
    race_served = registry.get("race")

    def swapper():
        start.wait()
        time.sleep(0.02)
        # the same race the HTTP swap verb runs: losing to the drain must
        # surface as an explicit draining error (503), never a 500
        try:
            race_served.swap(_net(5))
            with lock:
                outcomes.append("swap:200")
        except Exception as e:  # noqa: BLE001
            from deeplearning4j_tpu.serving import ServerDrainingError
            with lock:
                outcomes.append(f"swap:{type(e).__name__}")
                if not isinstance(e, ServerDrainingError):
                    violations.append(f"swap:{type(e).__name__}")

    def drainer():
        start.wait()
        # let real traffic land first (the 200-in-codes half of the
        # assertion), then race the drain against the rest of it
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with lock:
                if any(o == 200 for o in outcomes):
                    break
            time.sleep(0.005)
        drain_started[0] = time.monotonic()
        srv.drain(timeout=10)

    threads = [threading.Thread(target=predictor, args=(k,))
               for k in range(8)]
    threads.append(threading.Thread(target=swapper))
    threads.append(threading.Thread(target=drainer))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    hung = [t for t in threads if t.is_alive()]
    assert not hung, f"{len(hung)} threads hung past the drain"
    assert not violations, f"drain race violations: {violations}"
    codes = {o for o in outcomes if isinstance(o, int)}
    assert codes <= {200, 429, 503, 504}, codes
    assert 200 in codes                     # traffic really flowed


def test_drain_racing_swap_returns_503_not_500(registry):
    """A swap that loses the race with shutdown gets an explicit
    ServerDrainingError (HTTP 503), never a 500."""
    from deeplearning4j_tpu.serving import ServerDrainingError
    served = _deploy(registry, name="ds")
    served.shutdown(drain=False)
    with pytest.raises(ServerDrainingError):
        served.swap(_net(3))
    with pytest.raises(ServerDrainingError):
        served.rollback()


def test_fault_endpoint_gated_and_wedges_probes(registry):
    """/v1/faults exists only with enable_faults; a wedged server fails
    its probes the way the supervisor expects (500 on probe_error)."""
    from deeplearning4j_tpu.util.faults import serving_faults
    _deploy(registry, name="fz")
    plain = ModelServer(registry, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{plain.url}/v1/faults", b'{"probe_error": true}')
        assert e.value.code == 404          # hidden without the flag
        e.value.read()
    finally:
        plain.stop()
    srv = ModelServer(registry, port=0, enable_faults=True)
    try:
        code, doc = _post(f"{srv.url}/v1/faults", b'{"probe_error": true}')
        assert code == 200 and doc["probe_error"] is True
        for path in ("/healthz", "/readyz"):
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(srv.url + path, timeout=10)
            assert e.value.code == 500
            e.value.read()
        # unknown fault key -> clean 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{srv.url}/v1/faults", b'{"nope": 1}')
        assert e.value.code == 400
        e.value.read()
        # clearing restores the probes
        code, doc = _post(f"{srv.url}/v1/faults", b'{"probe_error": false}')
        assert code == 200
        assert urllib.request.urlopen(f"{srv.url}/healthz",
                                      timeout=10).status == 200
    finally:
        serving_faults().clear()
        srv.stop()


def test_fault_injection_is_per_server_instance(registry):
    """Two servers with their own ServingFaults instances: wedging one
    must not wedge the other (in-process fleet replicas rely on this)."""
    from deeplearning4j_tpu.util.faults import ServingFaults

    _deploy(registry, name="iso")
    srv_a = ModelServer(registry, port=0, enable_faults=True,
                        faults=ServingFaults())
    srv_b = ModelServer(registry, port=0, enable_faults=True,
                        faults=ServingFaults())
    try:
        code, _ = _post(f"{srv_a.url}/v1/faults", b'{"probe_error": true}')
        assert code == 200
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{srv_a.url}/healthz", timeout=10)
        assert e.value.code == 500
        e.value.read()
        # sibling server is untouched
        assert urllib.request.urlopen(f"{srv_b.url}/healthz",
                                      timeout=10).status == 200
    finally:
        srv_a.stop()
        srv_b.stop()


# -------------------------------------------------------------- satellites
def test_uint8_no_preprocessor_warns_once(caplog):
    from deeplearning4j_tpu.data import records as records_mod
    from deeplearning4j_tpu.data.records import (
        RecordReader, RecordReaderDataSetIterator,
    )

    class FakeImages(RecordReader):
        is_image = True

        def records(self):
            for i in range(4):
                yield (np.full((4, 4, 1), 100, np.uint8), i % 2)

    records_mod._warned_raw_uint8 = False
    it = RecordReaderDataSetIterator(FakeImages(), batch_size=2,
                                     label_index=-1, num_classes=2)
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        list(it)
        list(it)                         # second epoch: still once
    hits = [r for r in caplog.records
            if "no pre_processor" in r.getMessage()]
    assert len(hits) == 1
    # with a normalizer attached: silent
    from deeplearning4j_tpu.data.normalization import (
        ImagePreProcessingScaler,
    )
    records_mod._warned_raw_uint8 = False
    it2 = RecordReaderDataSetIterator(FakeImages(), batch_size=2,
                                      label_index=-1, num_classes=2)
    it2.set_pre_processor(ImagePreProcessingScaler())
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        list(it2)
    assert not [r for r in caplog.records
                if "no pre_processor" in r.getMessage()]


def test_device_norm_kill_switch_semantics(monkeypatch):
    """DL4J_TPU_DEVICE_NORM: only the documented '0' disables — 'true',
    'yes', '' behave as enabled, matching DL4J_TPU_FLASH/HOST_CAST."""
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.data.normalization import (
        ImagePreProcessingScaler, engaged_device_affine,
    )
    it = ArrayDataSetIterator(np.zeros((8, 4), "float32"),
                              np.zeros((8, 2), "float32"), batch_size=4)
    it.set_pre_processor(ImagePreProcessingScaler())
    for val, engaged in (("0", False), ("1", True), ("true", True),
                         ("yes", True)):
        monkeypatch.setenv("DL4J_TPU_DEVICE_NORM", val)
        with engaged_device_affine(it) as aff:
            assert (aff is not None) == engaged, (val, aff)
        assert it.pre_processor is not None      # always restored


def test_accum_partial_group_warns(caplog):
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    rs = np.random.RandomState(0)
    X = rs.randn(10, N_IN).astype("float32")     # batch 4 -> 4,4,2 tail
    Y = np.eye(N_OUT, dtype="float32")[rs.randint(0, N_OUT, 10)]
    it = ArrayDataSetIterator(X, Y, batch_size=4, drop_last=False)
    net = _net()
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        net.fit(it, epochs=1, accumulate_steps=2, prefetch=False)
    hits = [r for r in caplog.records
            if "accumulation group" in r.getMessage()]
    assert len(hits) == 1
    assert "shape changed" in hits[0].getMessage()


def test_bench_cache_dir_write_probe(tmp_path, monkeypatch):
    """cache_dir probes with a real create/remove — os.access(W_OK)
    answers yes to root even on a read-only mount, so only an actual
    failing open may engage the tempdir fallback."""
    import builtins
    import bench
    real_open = builtins.open
    # point the repo-local cache at tmp_path and make ITS opens fail the
    # way a read-only mount does for root (EROFS despite W_OK bits)
    monkeypatch.setattr(bench, "__file__",
                        str(tmp_path / "bench.py"), raising=True)
    denied = str(tmp_path / ".jaxcache")

    def deny(path, *a, **kw):
        if str(path).startswith(denied):
            raise OSError(30, "Read-only file system", str(path))
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", deny)
    d = bench.cache_dir()
    assert not d.startswith(denied)
    assert "dl4jtpu-jax-cache" in d
    # and with writable opens the repo-local dir is chosen
    monkeypatch.setattr(builtins, "open", real_open)
    assert bench.cache_dir() == denied
