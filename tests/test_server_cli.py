"""Nearest-neighbors REST server round-trip + CLI training entry smoke test
(DL4J NearestNeighborsServer.java:42, ParallelWrapperMain.java parity)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    NearestNeighborsClient, NearestNeighborsServer,
)


def test_nn_server_round_trip():
    rs = np.random.RandomState(0)
    pts = rs.randn(64, 8).astype("float32")
    with NearestNeighborsServer(pts, port=0) as server:
        client = NearestNeighborsClient(port=server.port)
        h = client.health()
        assert h == {"status": "ok", "points": 64, "dim": 8}
        # knn of an indexed point: nearest is itself at distance 0
        res = client.knn(index=5, k=3)
        assert res[0]["index"] == 5
        assert res[0]["distance"] == pytest.approx(0.0, abs=1e-6)
        # knn of a new vector matches brute force
        q = rs.randn(8).astype("float32")
        res = client.knn_new(q, k=5)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:5]
        assert [r["index"] for r in res] == list(brute)
        # insert + query finds the inserted point
        new_idx = client.insert(q)
        assert new_idx == 64
        res = client.knn_new(q, k=1)
        assert res[0]["index"] == 64
        assert res[0]["distance"] == pytest.approx(0.0, abs=1e-6)


def test_nn_server_rejects_bad_requests():
    pts = np.eye(4, dtype="float32")
    with NearestNeighborsServer(pts, port=0) as server:
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/knn",
            data=json.dumps({"index": 99, "k": 1}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 400


def test_cli_trains_and_saves(tmp_path):
    """ParallelWrapperMain flow: model zip in -> fit with wrapper knobs ->
    trained zip out, exercised through `python -m deeplearning4j_tpu.train`
    in a subprocess (real CLI surface)."""
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util.serialization import load_model, save_model

    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(5e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    model_in = str(tmp_path / "model.zip")
    model_out = str(tmp_path / "trained.zip")
    save_model(net, model_in)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.train",
         "--model", model_in, "--output", model_out,
         "--dataset", "iris", "--epochs", "30", "--batch-size", "32",
         "--mode", "sync"],
        capture_output=True, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    result = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert result["output"] == model_out
    assert np.isfinite(result["final_score"])
    trained = load_model(model_out)
    from deeplearning4j_tpu.data.fetchers import iris_dataset
    ds = iris_dataset()
    acc = trained.evaluate((ds.features, ds.labels)).accuracy()
    assert acc > 0.9, acc


def test_cli_npz_dataset_and_bad_npz(tmp_path):
    from deeplearning4j_tpu.train.cli import _load_data
    rs = np.random.RandomState(0)
    p = str(tmp_path / "data.npz")
    np.savez(p, features=rs.rand(20, 4).astype("float32"),
             labels=np.eye(2, dtype="float32")[rs.randint(0, 2, 20)])
    it = _load_data(p, batch_size=8)
    ds = next(iter(it))
    assert ds.features.shape == (8, 4)
    bad = str(tmp_path / "bad.npz")
    np.savez(bad, foo=np.zeros(3))
    with pytest.raises(SystemExit):
        _load_data(bad, batch_size=8)
