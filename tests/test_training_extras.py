"""Early stopping, transfer learning, gradient checks (DL4J
earlystopping/ + transferlearning/ + gradientcheck/ test strategy)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, LSTM, OutputLayer,
    RnnOutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning, TransferLearningHelper,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)


def _blobs(n=240, d=6, k=3, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    X = np.concatenate([centers[i] + rs.randn(n // k, d)
                        for i in range(k)]).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    perm = rs.permutation(n)
    return X[perm], Y[perm]


def _mlp(k=3, d=6, lr=2e-2, seed=0):
    return (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(lr)).list()
            .layer(DenseLayer(n_out=24, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=k, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(d)).build())


# ------------------------------------------------------------ early stopping
def test_early_stopping_max_epochs():
    X, Y = _blobs()
    net = MultiLayerNetwork(_mlp()).init()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator((X, Y)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(4)],
    )
    result = EarlyStoppingTrainer(cfg, net, (X, Y)).fit()
    assert result.termination_reason == "epoch"
    assert result.total_epochs == 4
    assert result.best_model is not None
    assert len(result.score_vs_epoch) == 4


def test_early_stopping_score_improvement():
    """Training on pure noise stops when validation loss stops improving."""
    rs = np.random.RandomState(1)
    X = rs.randn(120, 6).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 120)]
    net = MultiLayerNetwork(_mlp(lr=1e-3, seed=1)).init()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator((X, Y)),
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(2),
            MaxEpochsTerminationCondition(50),
        ],
    )
    result = EarlyStoppingTrainer(cfg, net, (X, Y)).fit()
    assert result.total_epochs <= 50
    assert result.best_model_score <= min(result.score_vs_epoch.values()) + 1e-9


def test_early_stopping_divergence_guard():
    X, Y = _blobs()
    net = MultiLayerNetwork(_mlp(lr=2e-2)).init()
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(10)],
        iteration_termination_conditions=[
            MaxScoreIterationTerminationCondition(max_score=1e-6)],
    )
    result = EarlyStoppingTrainer(cfg, net, (X, Y)).fit()
    assert result.termination_reason == "iteration"


# --------------------------------------------------------- transfer learning
def test_transfer_learning_freeze_and_replace_head():
    X, Y = _blobs()
    src = MultiLayerNetwork(_mlp()).init()
    src.fit((X, Y), epochs=3, batch_size=60)
    # new task with 5 classes: freeze features, new head
    net2 = (TransferLearning(src)
            .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(1e-2)))
            .set_feature_extractor(1)
            .remove_output_layer()
            .add_layer(OutputLayer(n_out=5, activation="softmax",
                                   loss="mcxent"))
            .build())
    assert len(net2.layers) == 3
    # frozen layer params match source
    np.testing.assert_allclose(np.asarray(net2.params["0"]["W"]),
                               np.asarray(src.params["0"]["W"]))
    frozen_w_before = np.asarray(net2.params["0"]["W"]).copy()
    Y5 = np.eye(5, dtype="float32")[np.random.RandomState(3).randint(0, 5, len(X))]
    net2.fit((X, Y5), epochs=2, batch_size=60)
    # frozen layer untouched by training
    np.testing.assert_allclose(np.asarray(net2.params["0"]["W"]),
                               frozen_w_before)


def test_transfer_learning_n_out_replace():
    X, Y = _blobs()
    src = MultiLayerNetwork(_mlp()).init()
    net2 = (TransferLearning(src)
            .n_out_replace(1, 32)
            .build())
    assert net2.layers[1].n_out == 32
    out = np.asarray(net2.output(X[:4]))
    assert out.shape == (4, 3)
    # layer 0 weights retained, layer 1/2 reinitialized with new shapes
    np.testing.assert_allclose(np.asarray(net2.params["0"]["W"]),
                               np.asarray(src.params["0"]["W"]))
    assert net2.params["1"]["W"].shape == (24, 32)
    assert net2.params["2"]["W"].shape == (32, 3)


def test_transfer_learning_helper_featurize():
    X, Y = _blobs()
    src = MultiLayerNetwork(_mlp()).init()
    src.fit((X, Y), epochs=3, batch_size=60)     # pretrain the body
    helper = TransferLearningHelper(src, frozen_until=1)
    feats = np.asarray(helper.featurize(X))
    assert feats.shape == (len(X), 16)
    helper.fit_featurized(feats, Y, epochs=10, batch_size=60)
    full = helper.unfrozen_network()
    acc = full.evaluate((X, Y)).accuracy()
    assert acc > 0.85, acc
    # featurized-head training must agree with full-network forward
    np.testing.assert_allclose(
        np.asarray(helper.head.output(feats[:8])),
        np.asarray(full.output(X[:8])), atol=1e-5)


# ------------------------------------------------------------ gradient check
def test_gradient_check_mlp():
    X, Y = _blobs(n=12)
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Sgd(1e-2)).l2(1e-3).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    res = check_gradients(net, X[:6], Y[:6], max_per_param=16)
    assert res.passed, res.failures[:3]


def test_gradient_check_cnn():
    rs = np.random.RandomState(0)
    X = rs.rand(4, 8, 8, 2).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 4)]
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Sgd(1e-2)).list()
            .layer(ConvolutionLayer(n_out=4, kernel=(3, 3),
                                    convolution_mode="same",
                                    activation="tanh"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2),
                                    pooling_type="max"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 2)).build())
    net = MultiLayerNetwork(conf).init()
    res = check_gradients(net, X, Y, max_per_param=12)
    assert res.passed, res.failures[:3]


def test_gradient_check_lstm_masked():
    rs = np.random.RandomState(0)
    X = rs.rand(3, 5, 4).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, (3, 5))]
    fmask = np.ones((3, 5), "float32")
    fmask[1, 3:] = 0
    fmask[2, 2:] = 0
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Sgd(1e-2)).list()
            .layer(LSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(4, 5)).build())
    net = MultiLayerNetwork(conf).init()
    res = check_gradients(net, X, Y, features_mask=fmask, max_per_param=10)
    assert res.passed, res.failures[:3]


def test_gradient_check_graph_residual():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    rs = np.random.RandomState(0)
    X = rs.rand(4, 6).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, 4)]
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(0)
                      .updater(Sgd(1e-2)))
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(6)))
    g.add_layer("d1", DenseLayer(n_out=6, activation="tanh"), "in")
    g.add_vertex("res", ElementWiseVertex(op="add"), "d1", "in")
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "res")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    res = check_gradients(net, X, Y, max_per_param=16)
    assert res.passed, res.failures[:3]


def test_profiler_listener_writes_trace(tmp_path):
    """ProfilerListener captures an XLA trace window during fit
    (SURVEY.md §5.1 tracing hook)."""
    import os
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.train import ProfilerListener
    rs = np.random.RandomState(0)
    X = rs.rand(64, 4).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, 64)]
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(1e-2))
            .list().layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    prof = ProfilerListener(str(tmp_path), start_iteration=2,
                            num_iterations=2)
    net.set_listeners(prof)
    net.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=2)
    assert prof.trace_dir == str(tmp_path)
    assert not prof._active
    # jax writes plugins/profile/<run>/ under the log dir
    found = []
    for root, dirs, files in os.walk(str(tmp_path)):
        found.extend(files)
    assert found, "no trace files written"


def test_divergence_listener_raises_on_nan_and_explosion():
    """Failure detection (SURVEY.md §5.3): NaN scores and loss explosions
    abort training instead of burning device hours."""
    from deeplearning4j_tpu.train import (
        DivergenceListener, TrainingDivergedError,
    )

    class FakeModel:
        pass

    lst = DivergenceListener()
    lst.iteration_done(FakeModel(), 0, 0, 1.0, 0.0, 8)
    with pytest.raises(TrainingDivergedError, match="non-finite"):
        lst.iteration_done(FakeModel(), 1, 0, float("nan"), 0.0, 8)

    lst2 = DivergenceListener(explosion_factor=100.0)
    for i in range(5):
        lst2.iteration_done(FakeModel(), i, 0, 1.0, 0.0, 8)
    with pytest.raises(TrainingDivergedError, match="exploded"):
        lst2.iteration_done(FakeModel(), 5, 0, 500.0, 0.0, 8)

    seen = []
    lst3 = DivergenceListener(
        on_divergence=lambda m, it, msg: seen.append((it, msg)))
    lst3.iteration_done(FakeModel(), 7, 0, float("inf"), 0.0, 8)
    assert seen and seen[0][0] == 7

    # integrates with a real fit: a huge lr makes the MLP explode
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    rs = np.random.RandomState(0)
    X = (rs.rand(64, 6) * 50).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, 64)]
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(500.0))
            .list().layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(DivergenceListener(explosion_factor=10.0, window=3))
    with pytest.raises(TrainingDivergedError):
        net.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=50)


def test_async_checkpoint_listener(tmp_path):
    """async_save moves serialization off the training thread; the saved
    zips restore bit-identically to the synchronous path."""
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.train import CheckpointListener
    from deeplearning4j_tpu.util.serialization import load_model
    rs = np.random.RandomState(0)
    X = rs.rand(64, 6).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, 64)]
    conf = (NeuralNetConfiguration.Builder().seed(4).updater(Sgd(1e-2))
            .list().layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    with CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                            keep_last=2, async_save=True) as ckpt:
        net.set_listeners(ckpt)
        net.fit(ArrayDataSetIterator(X, Y, batch_size=8), epochs=1)
    # retention: at most keep_last files remain
    import os
    files = sorted(f for f in os.listdir(str(tmp_path)) if f.endswith(".zip"))
    assert 1 <= len(files) <= 2, files
    restored = load_model(os.path.join(str(tmp_path), files[-1]))
    assert np.isfinite(float(np.asarray(restored.params_flat()).sum()))
    # the last checkpoint captured the params at its save iteration, not
    # the final ones (snapshot semantics) — restoring + refitting works
    restored.fit(ArrayDataSetIterator(X, Y, batch_size=8), epochs=1)
    assert np.isfinite(restored.score())


def test_computation_graph_copy_independent():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(0)
                      .updater(Sgd(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(4)))
    g.add_layer("d", DenseLayer(n_out=6), "in")
    g.add_layer("out", OutputLayer(n_out=2), "d")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    clone = net.copy()
    rs = np.random.RandomState(0)
    X = rs.rand(16, 4).astype("float32")
    np.testing.assert_allclose(np.asarray(clone.output(X)),
                               np.asarray(net.output(X)), atol=1e-6)
    from deeplearning4j_tpu.data.dataset import DataSet
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, 16)]
    net.fit(DataSet(X, Y), epochs=3)
    # clone unaffected by training the original
    assert not np.allclose(np.asarray(clone.params_flat()),
                           np.asarray(net.params_flat()))


def test_async_checkpoint_preserves_counters_and_head_survives_unfreeze():
    """Async checkpoints carry iteration/epoch counters (snapshot parity
    with sync saves), and the transfer-learning head survives training the
    unfrozen network (no donated-buffer aliasing)."""
    import os as _os
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.train import CheckpointListener
    from deeplearning4j_tpu.util.serialization import load_model
    rs = np.random.RandomState(1)
    X = rs.rand(64, 6).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 64)]
    conf = _mlp()
    net = MultiLayerNetwork(conf).init()
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        with CheckpointListener(td, save_every_n_iterations=4,
                                keep_last=1, async_save=True) as ckpt:
            net.set_listeners(ckpt)
            net.fit(ArrayDataSetIterator(X, Y, batch_size=8), epochs=1)
        files = [f for f in _os.listdir(td) if f.endswith(".zip")]
        assert len(files) == 1, files
        restored = load_model(_os.path.join(td, files[0]))
        assert restored.iteration_count == 4, restored.iteration_count

    src = MultiLayerNetwork(_mlp()).init()
    helper = TransferLearningHelper(src, frozen_until=1)
    feats = np.asarray(helper.featurize(X))
    helper.fit_featurized(feats, Y, epochs=2, batch_size=16)
    full = helper.unfrozen_network()
    full.fit((X, Y), epochs=2, batch_size=16)      # donates full's buffers
    # the head is still alive and usable afterwards
    out = np.asarray(helper.head.output(feats[:4]))
    assert np.all(np.isfinite(out))


def test_transfer_learning_graph_freeze_swap_head():
    """TransferLearning.GraphBuilder parity: freeze ancestors by vertex
    name, remove a head, attach a new one, keep trained torso weights."""
    import dataclasses  # noqa: F401
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.transferlearning import TransferLearningGraph

    rs = np.random.RandomState(0)
    centers = rs.randn(4, 6) * 3
    y4 = np.repeat(np.arange(4), 30)
    X = (centers[y4] + rs.randn(120, 6)).astype("float32")
    Y4 = np.eye(4, dtype="float32")[y4]

    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(1)
                      .updater(Adam(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(6)))
    g.add_layer("torso1", DenseLayer(n_out=16, activation="relu"), "in")
    g.add_layer("torso2", DenseLayer(n_out=12, activation="relu"), "torso1")
    g.add_layer("head", OutputLayer(n_out=4, activation="softmax",
                                    loss="mcxent"), "torso2")
    g.set_outputs("head")
    src = ComputationGraph(g.build()).init()
    src.fit((X, Y4), epochs=30)
    torso_w = np.asarray(src.params["torso1"]["W"]).copy()

    new = (TransferLearningGraph(src)
           .set_feature_extractor("torso2")
           .remove_vertex_and_connections("head")
           .add_layer("new_head", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "torso2")
           .set_outputs("new_head")
           .build())
    # trained torso carried over
    np.testing.assert_array_equal(np.asarray(new.params["torso1"]["W"]),
                                  torso_w)
    Y2 = np.eye(2, dtype="float32")[(y4 >= 2).astype(int)]
    new.fit((X, Y2), epochs=40)
    # frozen vertices bit-identical after training
    np.testing.assert_array_equal(np.asarray(new.params["torso1"]["W"]),
                                  torso_w)
    out = np.asarray(new.output(X))
    assert out.shape == (120, 2)
    acc = (out.argmax(1) == (y4 >= 2)).mean()
    assert acc > 0.7


def test_transfer_learning_graph_n_out_replace_reinits_consumer():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.transferlearning import TransferLearningGraph

    rs = np.random.RandomState(1)
    X = rs.randn(60, 5).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 60)]
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(2)
                      .updater(Adam(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(5)))
    g.add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"), "d")
    g.set_outputs("out")
    src = ComputationGraph(g.build()).init()
    new = TransferLearningGraph(src).n_out_replace("d", 20).build()
    assert np.asarray(new.params["d"]["W"]).shape == (5, 20)
    assert np.asarray(new.params["out"]["W"]).shape == (20, 3)
    assert np.asarray(new.output(X)).shape == (60, 3)


def test_transfer_learning_does_not_invalidate_source_network():
    """Regression: build() must COPY retained weights — the derived net's
    donated train step used to delete the source's buffers (aliasing)."""
    X, Y = _blobs()
    src = MultiLayerNetwork(_mlp()).init()
    src.fit((X, Y), epochs=2, batch_size=64)
    new = (TransferLearning(src)
           .set_feature_extractor(0)
           .remove_output_layer()
           .add_layer(OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
           .build())
    y2 = np.eye(2, dtype="float32")[np.zeros(len(X), int)]
    new.fit((X, y2), epochs=2, batch_size=64)
    # the source is still fully usable after the derived net trained
    out = np.asarray(src.output(X[:4]))
    assert np.isfinite(out).all()


def test_transfer_learning_graph_validation_and_merge_reinit():
    """Review r4: typo'd names fail fast; width changes propagate through
    parameterless merge vertices; frozen output vertices stay legal."""
    from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.transferlearning import TransferLearningGraph

    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(3)
                      .updater(Adam(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(6)))
    g.add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in")
    g.add_layer("d2", DenseLayer(n_out=8, activation="relu"), "in")
    g.add_vertex("m", MergeVertex(), "d1", "d2")
    g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"), "m")
    g.set_outputs("out")
    src = ComputationGraph(g.build()).init()

    with pytest.raises(ValueError, match="unknown vertex"):
        TransferLearningGraph(src).set_feature_extractor("dens1").build()
    with pytest.raises(ValueError, match="no n_out"):
        TransferLearningGraph(src).n_out_replace("m", 20).build()

    # width change through the merge: 'out' must be re-initialized
    new = TransferLearningGraph(src).n_out_replace("d1", 20).build()
    assert np.asarray(new.params["out"]["W"]).shape == (28, 3)
    X = np.random.RandomState(0).randn(4, 6).astype("float32")
    assert np.asarray(new.output(X)).shape == (4, 3)

    # freezing the whole net incl. the output vertex still builds + runs
    frozen = TransferLearningGraph(src).set_feature_extractor("out").build()
    Y = np.eye(3, dtype="float32")[np.zeros(4, int)]
    before = np.asarray(frozen.params["out"]["W"]).copy()
    frozen.fit((X, Y), epochs=2)
    np.testing.assert_array_equal(before,
                                  np.asarray(frozen.params["out"]["W"]))


def test_graph_fit_two_batch_list_not_misparsed():
    """fit([(X1,Y1),(X2,Y2)]) is a 2-batch list, not an array pair."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    rs = np.random.RandomState(1)
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(1)
                      .updater(Adam(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(4)))
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "in")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    batches = [DataSet(rs.randn(8, 4).astype("float32"),
                       np.eye(2, dtype="float32")[rs.randint(0, 2, 8)])
               for _ in range(2)]
    net.fit(batches)                    # 2-long list of DataSets
    assert net.iteration_count == 2


def test_transfer_learning_mln_width_change_through_batchnorm():
    """Review r4: n_out_replace must re-init past width-transparent
    layers (BatchNorm) down to the next projection."""
    from deeplearning4j_tpu.nn.layers import BatchNormalization
    conf = (NeuralNetConfiguration.Builder().seed(4).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=6, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    src = MultiLayerNetwork(conf).init()
    new = TransferLearning(src).n_out_replace(0, 20).build()
    assert np.asarray(new.params["0"]["W"]).shape == (5, 20)
    assert np.asarray(new.params["1"]["gamma"]).shape == (20,)
    assert np.asarray(new.params["2"]["W"]).shape == (20, 6)
    # the final output layer keeps its trained weights (width unchanged)
    np.testing.assert_array_equal(np.asarray(new.params["3"]["W"]),
                                  np.asarray(src.params["3"]["W"]))
    X = np.random.RandomState(0).randn(4, 5).astype("float32")
    assert np.asarray(new.output(X)).shape == (4, 3)


def test_frozen_lstm_keeps_streaming_state():
    """Review r4: a FrozenLayerWrapper'd LSTM must still dispatch through
    the stateful apply_seq path (rnn_time_step carries)."""
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 6)).build())
    src = MultiLayerNetwork(conf).init()
    frozen = (TransferLearning(src).set_feature_extractor(0)
              .build())
    rs = np.random.RandomState(1)
    x = rs.randn(2, 6, 3).astype("float32")
    full = np.asarray(frozen.output(x))
    frozen.rnn_clear_previous_state()
    stepped = np.concatenate(
        [np.asarray(frozen.rnn_time_step(x[:, t:t + 1])) for t in range(6)],
        axis=1)
    np.testing.assert_allclose(stepped, full, atol=1e-5)


def test_evaluate_roc_on_both_containers():
    """DL4J evaluateROC / evaluateROCMultiClass parity methods."""
    rs = np.random.RandomState(7)
    X = rs.randn(200, 4).astype("float32")
    y = (X[:, 0] + 0.3 * rs.randn(200) > 0).astype(int)
    Y = np.eye(2, dtype="float32")[y]
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit((X, Y), epochs=20, batch_size=50)
    roc = net.evaluate_roc((X, Y))
    assert roc.calculate_auc() > 0.9
    rocm = net.evaluate_roc_multi_class((X, Y))
    assert rocm.calculate_auc(0) > 0.9 and rocm.calculate_auc(1) > 0.9

    # graph variant
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(2)
                      .updater(Adam(1e-2)))
         .add_inputs("in").set_input_types(InputType.feed_forward(4)))
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "in")
    g.set_outputs("out")
    gnet = ComputationGraph(g.build()).init()
    gnet.fit((X, Y), epochs=150)   # one full-batch step per epoch
    assert gnet.evaluate_roc((X, Y), batch_size=64).calculate_auc() > 0.85
    gm = gnet.evaluate_roc_multi_class((X, Y), batch_size=64)
    assert gm.calculate_auc(1) > 0.85


def test_evaluate_roc_excludes_masked_steps():
    """Padded timesteps must not enter the ROC accumulators."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    rs = np.random.RandomState(8)
    conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 5)).build())
    net = MultiLayerNetwork(conf).init()
    X = rs.randn(8, 5, 3).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, (8, 5))]
    # padded tail steps carry all-zero labels that would poison the ROC
    lm = np.ones((8, 5), np.float32)
    lm[:, 3:] = 0.0
    Y[:, 3:] = 0.0
    roc = net.evaluate_roc(
        ExistingDataSetIterator([DataSet(X, Y, None, lm)]))
    # 8 examples x 3 valid steps accumulated, not 40
    assert sum(len(a) for a in roc._labels) == 24


def test_evaluate_roc_3d_unmasked_keeps_class_axis():
    """Review r4: unmasked (B,T,2) sequence labels must flatten to (N,2)
    so ROC strips the class axis instead of pooling both columns."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    rs = np.random.RandomState(9)
    conf = (NeuralNetConfiguration.Builder().seed(4).updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 4)).build())
    net = MultiLayerNetwork(conf).init()
    X = rs.randn(6, 4, 3).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, (6, 4))]
    roc = net.evaluate_roc(ExistingDataSetIterator([DataSet(X, Y)]))
    # 6 examples x 4 steps, ONE accumulated entry per step (class axis
    # stripped), not 48 pooled values
    assert sum(len(a) for a in roc._labels) == 24
    # trailing-singleton mask layout accepted
    lm = np.ones((6, 4, 1), np.float32)
    lm[:, 2:] = 0.0
    roc2 = net.evaluate_roc(
        ExistingDataSetIterator([DataSet(X, Y, None, lm)]))
    assert sum(len(a) for a in roc2._labels) == 12
