"""Telemetry subsystem tests — monitor/ (metrics registry + trace
spans), the UIServer /metrics route and error handling, and the
cross-subsystem instrumentation (fit loops, resilience, transport,
inference, PerformanceListener)."""
import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor.metrics import MetricsRegistry
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test sees a fresh global registry and a disabled, empty
    tracer (and leaves them that way for the rest of the suite)."""
    monitor.REGISTRY.reset()
    monitor.disable_tracing()
    monitor.clear_trace()
    yield
    monitor.REGISTRY.reset()
    monitor.disable_tracing()
    monitor.clear_trace()


def _small_net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _blobs(n=48, d=5, k=3, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, d).astype("float32")
    Y = np.eye(k, dtype="float32")[rs.randint(0, k, n)]
    return X, Y


# ------------------------------------------------------------- registry
def test_counter_concurrent_increments_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("hits_total", "hits", labels=("worker",))
    n_threads, per_thread = 8, 5000

    def work(i):
        for _ in range(per_thread):
            c.inc(worker=i % 2)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(worker=0) + c.value(worker=1) == n_threads * per_thread
    assert c.value(worker=0) == n_threads // 2 * per_thread


def test_histogram_concurrent_observes_are_exact():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "lat", buckets=(0.5,))
    threads = [threading.Thread(
        target=lambda: [h.observe(0.25) for _ in range(2000)])
        for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = h.snapshot()
    assert snap["count"] == 12000
    assert snap["buckets"]["0.5"] == 12000
    assert snap["sum"] == pytest.approx(3000.0)


def test_counter_rejects_decrease_and_gauge_allows_it():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("c_total", "c").inc(-1)
    g = reg.gauge("g", "g")
    g.set(5)
    g.dec(2)
    assert g.value() == 3


def test_registry_rejects_kind_and_label_mismatch():
    reg = MetricsRegistry()
    reg.counter("x_total", "x", labels=("a",))
    with pytest.raises(ValueError):
        reg.gauge("x_total", "x")
    with pytest.raises(ValueError):
        reg.counter("x_total", "x", labels=("b",))
    with pytest.raises(ValueError):                   # wrong label names
        reg.counter("x_total", "x", labels=("a",)).inc(b=1)


def test_histogram_bucket_edges_inclusive_upper():
    reg = MetricsRegistry()
    h = reg.histogram("h", "h", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 5.0, 7.0):
        h.observe(v)
    snap = h.snapshot()
    # `le` bounds are inclusive: 1.0 lands in le=1, 2.0 in le=2, 5.0 in
    # le=5; 7.0 only in +Inf; counts are cumulative
    assert snap["buckets"] == {"1": 2, "2": 4, "5": 5, "+Inf": 6}
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(17.0)


def test_histogram_buckets_mismatch_rejected():
    reg = MetricsRegistry()
    reg.histogram("hb", "h", buckets=(1.0, 2.0))
    # same buckets (any order / explicit +Inf) re-resolve fine
    assert reg.histogram("hb", "h", buckets=(2.0, 1.0, float("inf"))) \
        is reg.histogram("hb", "h", buckets=(1.0, 2.0))
    with pytest.raises(ValueError):   # silently mismatched edges would
        reg.histogram("hb", "h", buckets=(1.0, 3.0))


def test_histogram_explicit_inf_bucket_and_empty_rejected():
    reg = MetricsRegistry()
    h = reg.histogram("h2", "h", buckets=(1.0, float("inf")))
    h.observe(0.5)
    h.observe(9.0)
    assert h.snapshot()["buckets"] == {"1": 1, "+Inf": 2}
    with pytest.raises(ValueError):
        reg.histogram("h3", "h", buckets=())


def test_prometheus_text_golden():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", "Total requests", labels=("method",))
    c.inc(3, method="get")
    c.inc(1.5, method="post")
    reg.gauge("queue_depth", "Depth").set(2)
    h = reg.histogram("latency_seconds", "Latency", buckets=(0.1, 1.0))
    for v in (0.0625, 0.5, 5.0):      # binary-exact values: sum is exact
        h.observe(v)
    expected = (
        "# HELP latency_seconds Latency\n"
        "# TYPE latency_seconds histogram\n"
        'latency_seconds_bucket{le="0.1"} 1\n'
        'latency_seconds_bucket{le="1"} 2\n'
        'latency_seconds_bucket{le="+Inf"} 3\n'
        "latency_seconds_sum 5.5625\n"
        "latency_seconds_count 3\n"
        "# HELP queue_depth Depth\n"
        "# TYPE queue_depth gauge\n"
        "queue_depth 2\n"
        "# HELP requests_total Total requests\n"
        "# TYPE requests_total counter\n"
        'requests_total{method="get"} 3\n'
        'requests_total{method="post"} 1.5\n'
    )
    assert reg.prometheus_text() == expected


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.counter("e_total", "e", labels=("p",)).inc(p='a"b\\c\nd')
    line = [ln for ln in reg.prometheus_text().splitlines()
            if ln.startswith("e_total{")][0]
    assert line == 'e_total{p="a\\"b\\\\c\\nd"} 1'


def test_dump_and_summary_shapes():
    reg = MetricsRegistry()
    reg.counter("a_total", "a").inc(2)
    reg.histogram("b_seconds", "b", buckets=(1.0,)).observe(0.5)
    d = reg.dump()
    assert d["a_total"]["type"] == "counter"
    assert d["a_total"]["series"][0] == {"labels": {}, "value": 2.0}
    assert d["b_seconds"]["series"][0]["buckets"] == {"1": 1, "+Inf": 1}
    s = reg.summary()
    assert s["a_total"] == 2.0
    assert s["b_seconds"]["count"] == 1
    json.dumps(s)                     # summary must be JSON-serializable


# -------------------------------------------------------------- tracing
def test_span_is_noop_while_disabled():
    s1 = monitor.span("x", a=1)
    s2 = monitor.span("y")
    assert s1 is s2                   # shared null object: zero allocation
    with s1:
        pass
    monitor.add_span("z", 0.0, 1.0)
    monitor.instant("i")
    assert monitor.trace_events() == []


def test_trace_spans_nest_and_threads_are_distinct(tmp_path):
    monitor.enable_tracing()
    with monitor.span("parent", phase="outer"):
        with monitor.span("child"):
            pass

    def worker():
        with monitor.span("worker_span"):
            pass

    t = threading.Thread(target=worker, name="trace-worker")
    t.start()
    t.join()
    monitor.instant("mark", step=3)
    path = str(tmp_path / "trace.json")
    n = monitor.save_trace(path)
    assert n == 4
    assert monitor.trace_events() == []           # save drains by default
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    spans = {e["name"]: e for e in events if e.get("ph") == "X"}
    parent, child = spans["parent"], spans["child"]
    assert parent["args"] == {"phase": "outer"}
    assert parent["tid"] == child["tid"]
    eps = 1.0
    assert parent["ts"] - eps <= child["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + eps
    assert spans["worker_span"]["tid"] != parent["tid"]
    assert len({e["tid"] for e in events if e.get("ph") == "X"}) == 2
    names = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "trace-worker" in names
    marks = [e for e in events if e.get("ph") == "i"]
    assert marks and marks[0]["name"] == "mark" \
        and marks[0]["args"] == {"step": 3}


# ------------------------------------------------- fit instrumentation
def test_fit_records_metrics_and_nested_trace(tmp_path):
    monitor.enable_tracing()
    X, Y = _blobs()
    net = _small_net()
    net.fit((X, Y), epochs=2, batch_size=16, scan_steps=1)
    reg = monitor.REGISTRY
    assert reg.collect("train_iterations_total").value() == 6
    assert reg.collect("train_examples_total").value() == 96
    assert np.isfinite(reg.collect("train_score").value())
    assert reg.collect("train_step_seconds").snapshot()["count"] == 6
    assert reg.collect("train_host_sync_seconds").snapshot()["count"] == 6
    # prefetch wrap is on by default: ETL series must be present too
    assert reg.collect("etl_batches_prefetched_total").value() == 6
    assert reg.collect("etl_fetch_wait_seconds").snapshot()["count"] >= 6

    path = str(tmp_path / "fit_trace.json")
    monitor.save_trace(path)
    with open(path) as f:
        events = [e for e in json.load(f)["traceEvents"]
                  if e.get("ph") == "X"]
    epochs = [e for e in events if e["name"] == "train/epoch"]
    steps = [e for e in events if e["name"] == "train/step"]
    etls = [e for e in events if e["name"] == "train/etl"]
    stages = [e for e in events if e["name"] == "etl/stage"]
    assert len(epochs) == 2 and len(steps) == 6 and len(etls) == 6
    eps = 1.0
    for s in steps:                   # every step nests inside an epoch
        assert any(ep["tid"] == s["tid"]
                   and ep["ts"] - eps <= s["ts"]
                   and s["ts"] + s["dur"] <= ep["ts"] + ep["dur"] + eps
                   for ep in epochs)
    # prefetch staging runs on its own thread track
    assert stages and stages[0]["tid"] != steps[0]["tid"]


def test_fit_scan_path_records_iterations():
    X, Y = _blobs()
    net = _small_net()
    net.fit((X, Y), epochs=1, batch_size=16, scan_steps=3)
    reg = monitor.REGISTRY
    assert reg.collect("train_iterations_total").value() == 3
    assert reg.collect("train_chunks_dispatched_total").value() >= 1


def test_performance_listener_consistent_and_feeds_registry():
    from deeplearning4j_tpu.train.listeners import PerformanceListener
    X, Y = _blobs()
    net = _small_net()
    lst = PerformanceListener(frequency=1, report=False)
    net.set_listeners(lst)
    net.fit((X, Y), epochs=1, batch_size=16, scan_steps=1)
    assert lst.history
    for rec in lst.history:
        assert rec["examples_per_sec"] == rec["samples_per_sec"]
        assert "etl_ms" in rec
    reg = monitor.REGISTRY
    assert reg.collect("train_examples_per_sec").value() > 0
    assert reg.collect("train_batches_per_sec").value() > 0
    assert reg.collect("train_etl_seconds").snapshot()["count"] \
        == len(lst.history)


# ------------------------------------------------ resilience integration
def test_resilience_nan_skip_increments_counter(tmp_path):
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.train.resilience import ResilientTrainer
    from deeplearning4j_tpu.util.faults import FaultInjector
    X, Y = _blobs()
    net = _small_net()
    report = ResilientTrainer(
        net, str(tmp_path / "ck"), save_every_n_iterations=100,
        injector=FaultInjector(nan_at=[1]),
    ).fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
    assert report.skipped_steps == 1
    reg = monitor.REGISTRY
    assert reg.collect("resilience_steps_skipped_total").value() == 1
    assert reg.collect("resilience_checkpoints_written_total").value() >= 1
    assert reg.collect("resilience_checkpoint_save_seconds"
                       ).snapshot()["count"] >= 1
    assert reg.collect("train_iterations_total").value() \
        == report.applied_steps


# ------------------------------------------------- transport integration
def test_transport_metrics_bytes_and_messages():
    from deeplearning4j_tpu.parallel.transport import SocketTransport
    base = 30530 + os.getpid() % 200
    msg = (np.arange(3, dtype=np.int32), np.ones(3, np.int8), 1.0)
    with SocketTransport(0, 2, base_port=base) as t0, \
            SocketTransport(1, 2, base_port=base) as t1:
        t0.broadcast(0, msg)
        t1.broadcast(1, msg)
        t0.recv(1, timeout=30)
        t1.recv(1, timeout=30)
        reg = monitor.REGISTRY
        sent = reg.collect("transport_bytes_sent_total")
        rcvd = reg.collect("transport_bytes_received_total")
        assert sent.value(rank=0) == t0.bytes_sent > 0
        # the wire is lossless: rank 1's inbound bytes == rank 0's out
        assert rcvd.value(rank=1) == sent.value(rank=0)
        msgs = reg.collect("transport_messages_sent_total")
        assert msgs.value(rank=0) == 1 and msgs.value(rank=1) == 1
        assert reg.collect("transport_send_seconds"
                           ).snapshot(rank=0)["count"] == 1
        assert reg.collect("transport_recv_wait_seconds"
                           ).snapshot(rank=0)["count"] == 1
        assert reg.collect("transport_connects_total").value(rank=0) == 1


# ------------------------------------------------- inference integration
def test_inference_metrics_latency_and_batches():
    from deeplearning4j_tpu.parallel.inference import (
        InferenceMode, ParallelInference,
    )
    net = _small_net()
    x = np.random.RandomState(3).randn(4, 5).astype("float32")
    with ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_batch_size=8) as pi:
        y = pi.output(x)
    assert y.shape == (4, 3)
    reg = monitor.REGISTRY
    assert reg.collect("inference_requests_total").value() == 1
    assert reg.collect("inference_request_seconds"
                       ).snapshot()["count"] == 1
    bsnap = reg.collect("inference_batch_size").snapshot()
    assert bsnap["count"] == 1 and bsnap["sum"] == 4


# ------------------------------------------------------ /metrics route
def _http_error(url, data=None):
    try:
        urllib.request.urlopen(url, data=data, timeout=10)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())
    raise AssertionError(f"expected an HTTP error from {url}")


def test_ui_server_serves_prometheus_metrics():
    from deeplearning4j_tpu.ui.server import UIServer
    monitor.counter("scrape_probe_total", "probe").inc(7)
    monitor.histogram("scrape_lat_seconds", "probe",
                      buckets=(0.5,)).observe(0.1)
    server = UIServer(port=0)
    try:
        resp = urllib.request.urlopen(server.url + "metrics", timeout=10)
        body = resp.read().decode()
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert "scrape_probe_total 7" in body
        assert 'scrape_lat_seconds_bucket{le="0.5"} 1' in body
        assert "# TYPE scrape_probe_total counter" in body
    finally:
        server.stop()


def test_ui_server_clean_errors_not_500():
    from deeplearning4j_tpu.ui.server import UIServer
    server = UIServer(port=0)
    try:
        code, body = _http_error(server.url + "train/data?sid=nope&after=0")
        assert code == 404 and "unknown session" in body["error"]
        code, body = _http_error(server.url + "train/data?sid=x&after=zzz")
        assert code == 400 and "after" in body["error"]
        # well-formed JSON that is not an object must 400, not 500
        code, body = _http_error(server.url + "remoteReceive",
                                 data=b"[1, 2, 3]")
        assert code == 400 and "bad body" in body["error"]
        code, body = _http_error(server.url + "tsne/post/s",
                                 data=b"not json at all")
        assert code == 400 and "bad body" in body["error"]
        code, body = _http_error(server.url + "no/such/route")
        assert code == 404
    finally:
        server.stop()


# ------------------------------------------------------------ CI smoke
@pytest.mark.slow
def test_telemetry_smoke_tool(tmp_path):
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "telemetry_smoke.py"),
         "--trace-out", out],
        cwd=_REPO, capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    summary = json.loads(r.stdout)
    assert summary["ok"] and summary["metric_families"] >= 12
    assert os.path.exists(out)
